"""Elastic multi-host rendezvous (resilience/rendezvous.py + the
multihost.py overlay): membership leases, deadline-bounded collectives,
generation resize, version-skew refusal at join, and the world-routed
topology reads — host churn as an expected input, proven at thread
scale (the process-scale proof is `make host-smoke`)."""
import json
import os
import threading
import time

import pytest

from deep_vision_tpu.resilience.rendezvous import (
    ENV_GENERATION,
    HostLostError,
    HostSupervisor,
    Rendezvous,
    RendezvousRefused,
    RendezvousTimeout,
    WorldResized,
    WorldView,
    versions_compatible,
)

FAST = dict(heartbeat_s=0.1, poll_s=0.01)


def join_world(root, hosts, expect=None, timeout_s=20.0, **kw):
    """Join `hosts` concurrently (threads); returns {host: (rdzv, view)}."""
    expect = expect if expect is not None else len(hosts)
    out, errs = {}, {}

    def run(h):
        r = Rendezvous(root, h, **FAST, **kw)
        try:
            out[h] = (r, r.join(expect_hosts=expect, timeout_s=timeout_s))
        except Exception as e:
            errs[h] = e

    ts = [threading.Thread(target=run, args=(h,)) for h in hosts]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout_s + 10)
    return out, errs


class FakeJournal:
    def __init__(self):
        self.rows = []

    def write(self, event, **fields):
        self.rows.append({"event": event, **fields})

    def of(self, event):
        return [r for r in self.rows if r["event"] == event]

    def add_tap(self, fn):  # observer hooks (GoodputMeter, AlertEngine):
        pass                # inert here — these tests assert row trails

    def add_closer(self, fn):
        pass


# -- WorldView + version handshake (pure) --------------------------------------

class TestWorldView:
    def test_dense_ranks_and_shard(self):
        v = WorldView(generation=2, hosts=("a", "b", "c"), host="b")
        assert (v.rank, v.world_size) == (1, 3)
        assert v.shard() == (1, 3)
        assert WorldView(2, ("a", "c"), "c").shard() == (1, 2)

    def test_versions_compatible(self):
        ok, _ = versions_compatible({"client_version": "x"},
                                    {"client_version": "x"})
        assert ok
        ok, detail = versions_compatible({"client_version": "x"},
                                         {"client_version": "y"})
        assert not ok and "client_version" in detail
        # a side that reports nothing is not a mismatch (fail open on
        # missing introspection, closed on a real disagreement)
        ok, _ = versions_compatible({}, {"client_version": "x"})
        assert ok
        assert versions_compatible({"platform_version": "a"},
                                   {"platform_version": "b"})[0] is False


# -- join / membership / barriers ----------------------------------------------

class TestJoin:
    def test_three_hosts_form_generation_zero(self, tmp_path):
        out, errs = join_world(str(tmp_path), ["h0", "h1", "h2"])
        assert not errs
        views = {h: v for h, (_, v) in out.items()}
        assert all(v.generation == 0 for v in views.values())
        assert all(v.hosts == ("h0", "h1", "h2") for v in views.values())
        assert [views[f"h{i}"].rank for i in range(3)] == [0, 1, 2]
        assert all(v.coordinator for v in views.values())
        for r, _ in out.values():
            r.leave()

    def test_join_timeout_names_who_showed_up(self, tmp_path):
        r = Rendezvous(str(tmp_path), "only", **FAST)
        with pytest.raises(RendezvousTimeout) as ei:
            r.join(expect_hosts=2, timeout_s=0.5)
        assert "only" in str(ei.value)

    def test_version_skewed_joiner_refused_in_seconds(self, tmp_path):
        incumbent = Rendezvous(str(tmp_path), "good", **FAST,
                               client_version="jax 0.4.37")
        incumbent.start_heartbeat()
        skewed = Rendezvous(str(tmp_path), "stale", **FAST,
                            client_version="jax 0.3.25")
        t0 = time.time()
        with pytest.raises(RendezvousRefused) as ei:
            skewed.join(expect_hosts=2, timeout_s=30.0)
        assert ei.value.kind == "version_skew"
        # refused by the handshake, not by burning the join deadline
        assert time.time() - t0 < 5.0
        # the refusal ledger records why this host never made a world
        refusal = json.load(open(tmp_path / "refused" / "stale.json"))
        assert refusal["kind"] == "version_skew"
        incumbent.leave()

    def test_skewed_host_joining_first_does_not_poison_the_world(
            self, tmp_path):
        """The version reference is the MAJORITY, not merely the
        earliest joiner: a stale host that happens to write its member
        record first must be the one refused — not trick every correct
        host into self-refusing."""
        stale = Rendezvous(str(tmp_path), "aa-stale-but-first", **FAST,
                           client_version="jax 0.3")
        stale.start_heartbeat()
        time.sleep(2 * FAST["heartbeat_s"])  # it is unambiguously first
        out, errs = join_world(str(tmp_path), ["m", "n"], expect=2,
                               client_version="jax 0.4")
        assert not errs, errs
        for _, v in out.values():
            assert v.hosts == ("m", "n")
        refusal = json.load(
            open(tmp_path / "refused" / "aa-stale-but-first.json"))
        assert refusal["kind"] == "version_skew"
        for r, _ in out.values():
            r.leave()
        stale.leave()

    def test_tiebreak_disagreement_gets_grace_before_self_refusal(
            self, tmp_path):
        """The race inside the majority vote: a correct host whose
        compatible peers' member records have not landed yet sees a 1-1
        tie against a stale first-writer and must NOT self-refuse on the
        spot — the tie gets a grace window (more voters are milliseconds
        away). A tie that PERSISTS past the grace is a genuine 1-vs-1
        skew and still refuses in ~2 heartbeats."""
        m = Rendezvous(str(tmp_path), "m", **FAST, client_version="jax 0.4")
        members = {
            "stale": {"host": "stale", "ts": time.time(), "joined_ts": 1.0,
                      "client_version": "jax 0.3"},
            "m": {"host": "m", "ts": time.time(), "joined_ts": 2.0,
                  "client_version": "jax 0.4"},
        }
        m._check_admission(members)  # tie: grace, not refusal
        assert m._tie_since is not None
        # the tie persisting past the grace window IS the 1-vs-1 skew
        m._tie_since = time.time() - 10 * FAST["heartbeat_s"]
        with pytest.raises(RendezvousRefused) as ei:
            m._check_admission(members)
        assert ei.value.kind == "version_skew"
        # ...while a compatible peer landing mid-grace breaks the tie:
        # the majority flips, the latch clears, nobody correct refuses
        m2 = Rendezvous(str(tmp_path / "b"), "m", **FAST,
                        client_version="jax 0.4")
        m2._check_admission(dict(members))
        assert m2._tie_since is not None
        members["n"] = {"host": "n", "ts": time.time(), "joined_ts": 3.0,
                        "client_version": "jax 0.4"}
        m2._check_admission(members)
        assert m2._tie_since is None
        # ...and a sweep that has not seen OUR OWN record yet (first
        # poll / shared-FS listing lag) must count our self-vote: one
        # stale record alone is a 1-1 tie, not a strict majority —
        # instant refusal here would bypass the grace entirely
        m3 = Rendezvous(str(tmp_path / "c"), "m", **FAST,
                        client_version="jax 0.4")
        m3._check_admission({
            "stale": {"host": "stale", "ts": time.time(), "joined_ts": 1.0,
                      "client_version": "jax 0.3"}})
        assert m3._tie_since is not None  # grace armed, nobody refused

    def test_fresh_fleet_over_stale_records_forms_next_generation(
            self, tmp_path):
        # yesterday's run left gen/0.json + dead member records: a
        # re-joining fleet (same host ids!) must form generation 1, not
        # adopt the stale record with its dead coordinator
        out, errs = join_world(str(tmp_path), ["a", "b"])
        assert not errs
        gen0_coord = out["a"][1].coordinator
        for r, _ in out.values():
            r._hb_stop.set()  # the whole world dies (leases lapse,
            # member files remain — the SIGKILL shape)
        time.sleep(4 * FAST["heartbeat_s"])
        out2, errs2 = join_world(str(tmp_path), ["a", "b"])
        assert not errs2, errs2
        for _, v in out2.values():
            assert v.generation == 1
            assert v.coordinator != gen0_coord
        for r, _ in out2.values():
            r.leave()

    def test_joiner_grows_a_running_world_at_the_next_resize(
            self, tmp_path):
        # the host_joined path: a new host's join() waits (never
        # overwrites the running world); the incumbents' next resize()
        # adopts every live compatible member, joiner included
        out, errs = join_world(str(tmp_path), ["b", "c"])
        assert not errs
        joined = {}

        def late_join():
            r = Rendezvous(str(tmp_path), "a", **FAST)  # sorts FIRST:
            # a waiting joiner must also never be elected resize leader
            joined["a"] = (r, r.join(expect_hosts=3, timeout_s=20))

        tj = threading.Thread(target=late_join)
        tj.start()
        time.sleep(3 * FAST["heartbeat_s"])  # joiner is waiting, world
        assert "a" not in joined             # untouched
        res = {}

        def rs(h):
            res[h] = out[h][0].resize()

        ts = [threading.Thread(target=rs, args=(h,)) for h in ("b", "c")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        tj.join(30)
        # record order is RANK order, leader first: rank 0 must be the
        # incumbent that allocated (and can bind) the coordinator port,
        # never the lexicographically-lower joiner
        assert res["b"].hosts == ("b", "a", "c")
        assert res["b"].rank == 0
        assert joined["a"][1].hosts == ("b", "a", "c")
        assert joined["a"][1].rank == 1
        assert joined["a"][1].generation == res["b"].generation == 1
        for h in ("b", "c"):
            out[h][0].leave()
        joined["a"][0].leave()

    def test_attached_survivors_still_read_as_a_running_world(
            self, tmp_path, monkeypatch):
        # a post-reexec attach re-stamps the process's construction
        # time, but _adopt clamps joined_ts back to the record: a
        # replacement joiner must WAIT for a resize, not decide the
        # world is dead and squat the next generation
        out, errs = join_world(str(tmp_path), ["b", "c"])
        assert not errs
        monkeypatch.setenv(ENV_GENERATION, "0")
        fresh = {}

        def reattach(h):
            r = Rendezvous(str(tmp_path), h, **FAST)  # joined_ts = now,
            fresh[h] = r                              # AFTER the record
            r.attach(timeout_s=10)

        ts = [threading.Thread(target=reattach, args=(h,))
              for h in ("b", "c")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(15)
        monkeypatch.delenv(ENV_GENERATION)
        joiner = Rendezvous(str(tmp_path), "a", **FAST)
        with pytest.raises(RendezvousTimeout):
            joiner.join(expect_hosts=3, timeout_s=1.0)
        assert joiner.read_generation(1) is None  # no squatted record
        for r, _ in out.values():
            r.leave()
        for r in fresh.values():
            r.leave()

    def test_dead_fleets_stale_records_do_not_vote_on_versions(
            self, tmp_path):
        # a crashed 3-host run on old versions leaves stale member
        # records; the fresh 2-host fleet on NEW versions must not let
        # the corpses out-vote it into self-refusal
        for i in range(3):
            old = Rendezvous(str(tmp_path), f"dead{i}", **FAST,
                             client_version="jax OLD")
            old._joined_ts = time.time() - 100
            old.touch()  # record on disk, lease long lapsed
        time.sleep(4 * FAST["heartbeat_s"])
        out, errs = join_world(str(tmp_path), ["x", "y"], expect=2,
                               client_version="jax NEW")
        assert not errs, errs
        for r, _ in out.values():
            r.leave()

    def test_refusal_marker_retires_after_the_host_is_fixed(self, tmp_path):
        # refused once for skew, upgraded, relaunched under the SAME id:
        # the stale marker must retire, not ban the id forever
        incumbent = Rendezvous(str(tmp_path), "good", **FAST,
                               client_version="v2")
        incumbent.start_heartbeat()
        stale = Rendezvous(str(tmp_path), "flaky", **FAST,
                           client_version="v1")
        with pytest.raises(RendezvousRefused):
            stale.join(expect_hosts=2, timeout_s=10)
        fixed = {}

        def rejoin():
            r = Rendezvous(str(tmp_path), "flaky", **FAST,
                           client_version="v2")
            fixed["view"] = r.join(expect_hosts=2, timeout_s=20)
            fixed["r"] = r

        tw = threading.Thread(target=rejoin)
        tw.start()
        # the incumbent forms the world with the fixed host
        inc = {}

        def inc_join():
            inc["view"] = incumbent.join(expect_hosts=2, timeout_s=20)

        ti = threading.Thread(target=inc_join)
        ti.start()
        tw.join(30)
        ti.join(30)
        assert set(fixed["view"].hosts) == {"good", "flaky"}
        incumbent.leave()
        fixed["r"].leave()

    def test_leader_excludes_skewed_member_that_skipped_self_check(
            self, tmp_path):
        # the skewed member's record is on disk but it never ran the
        # self-check (a buggy/old joiner): the leader's compatible-set
        # filter must exclude it AND leave the refusal marker. The
        # version reference is the EARLIEST joiner (the incumbent
        # world), so the late skewed record loses.
        r = Rendezvous(str(tmp_path), "a", **FAST, client_version="v1")
        members = {
            "a": {"host": "a", "ts": time.time(), "joined_ts": 1.0,
                  "client_version": "v1"},
            "b": {"host": "b", "ts": time.time(), "joined_ts": 2.0,
                  "client_version": "v1"},
            "z": {"host": "z", "ts": time.time(), "joined_ts": 3.0,
                  "client_version": "v2-skewed"},
        }
        compat = r._compatible(members)
        assert sorted(compat) == ["a", "b"]
        refusal = json.load(open(tmp_path / "refused" / "z.json"))
        assert refusal["kind"] == "version_skew"


class TestBarriers:
    def test_agree_is_global_or_and_reusable(self, tmp_path):
        out, errs = join_world(str(tmp_path), ["a", "b"])
        assert not errs
        for flags, want in [((True, False), True), ((False, False), False)]:
            res = {}

            def run(h, f):
                res[h] = out[h][0].agree("stop", f, timeout_s=10)

            ts = [threading.Thread(target=run, args=(h, f))
                  for h, f in zip(("a", "b"), flags)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(15)
            assert res == {"a": want, "b": want}
        for r, _ in out.values():
            r.leave()

    def test_dead_peer_yields_host_lost_not_hang(self, tmp_path):
        """THE acceptance property: a barrier with a dead peer raises a
        typed HostLostError within the heartbeat deadline — never an
        indefinite hang ended by a watchdog dump."""
        out, errs = join_world(str(tmp_path), ["a", "b"])
        assert not errs
        ra, rb = out["a"][0], out["b"][0]
        rb._hb_stop.set()  # the SIGKILL stand-in: heartbeats stop dead
        t0 = time.time()
        with pytest.raises(HostLostError) as ei:
            ra.barrier("after-death", timeout_s=30.0)
        elapsed = time.time() - t0
        assert ei.value.host == "b"
        assert ei.value.generation == 0
        # within the lease deadline (0.3s) + poll slack, nowhere near
        # the 30s barrier deadline
        assert elapsed < 5.0
        ra.leave()

    def test_live_stragglers_hit_the_deadline_as_timeout(self, tmp_path):
        # everyone alive but out of step = a logic bug, typed as timeout
        out, errs = join_world(str(tmp_path), ["a", "b"])
        assert not errs
        with pytest.raises(RendezvousTimeout):
            out["a"][0].barrier("nobody-else-comes", timeout_s=0.5)
        for r, _ in out.values():
            r.leave()

    def test_check_names_the_corpse_with_lease_gap(self, tmp_path):
        out, errs = join_world(str(tmp_path), ["a", "b"])
        assert not errs
        ra, rb = out["a"][0], out["b"][0]
        ra.check()  # everyone alive: clean
        rb._hb_stop.set()
        time.sleep(4 * FAST["heartbeat_s"])
        with pytest.raises(HostLostError) as ei:
            ra.check()
        assert ei.value.host == "b"
        assert ei.value.lease_gap_s is not None
        assert ei.value.lease_gap_s > 0
        ra.leave()


# -- resize: the N -> M contract -----------------------------------------------

class TestResize:
    def test_three_to_two_re_ranks_densely(self, tmp_path):
        out, errs = join_world(str(tmp_path), ["h0", "h1", "h2"])
        assert not errs
        out["h1"][0]._hb_stop.set()  # kill the MIDDLE host: h2 must
        time.sleep(4 * FAST["heartbeat_s"])  # re-rank 2 -> 1
        res = {}

        def run(h):
            res[h] = out[h][0].resize()

        ts = [threading.Thread(target=run, args=(h,)) for h in ("h0", "h2")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert res["h0"].generation == 1 and res["h2"].generation == 1
        assert res["h0"].hosts == ("h0", "h2")
        assert res["h0"].rank == 0 and res["h2"].rank == 1
        # fresh coordinator per generation: the old leader's dead port
        # must not be re-dialed
        assert res["h0"].coordinator == res["h2"].coordinator
        for h in ("h0", "h2"):
            out[h][0].leave()

    def test_resize_rederives_disjoint_covering_shards_and_batch(
            self, tmp_path):
        """Satellite regression: a 3->2 resize re-derives host_shard /
        per_host_batch_size from the NEW world (the fixed-world
        process_count() read is gone)."""
        from deep_vision_tpu.parallel import multihost as mh

        try:
            shards_by_gen = {}
            for gen, hosts in [(0, ("h0", "h1", "h2")), (1, ("h0", "h2"))]:
                shards = []
                for h in hosts:
                    mh.install_world(WorldView(gen, hosts, h))
                    assert mh.process_count() == len(hosts)
                    shards.append(mh.host_shard())
                    # global batch 12 redistributes exactly
                    assert mh.per_host_batch_size(12) == 12 // len(hosts)
                shards_by_gen[gen] = shards
            assert shards_by_gen[0] == [(0, 3), (1, 3), (2, 3)]
            assert shards_by_gen[1] == [(0, 2), (1, 2)]
            # disjoint + covering at both worlds
            for gen, shards in shards_by_gen.items():
                assert sorted(s[0] for s in shards) == list(
                    range(len(shards)))
        finally:
            mh.clear_world()

    def test_indivisible_batch_after_resize_is_loud(self):
        from deep_vision_tpu.parallel import multihost as mh

        try:
            mh.install_world(WorldView(1, ("a", "b"), "a"))
            with pytest.raises(ValueError):
                mh.per_host_batch_size(13)
        finally:
            mh.clear_world()


# -- shard_for_host under world resize (property) ------------------------------

class TestShardForHostResize:
    def test_disjoint_and_covering_for_any_world_size(self):
        from deep_vision_tpu.data.service import shard_for_host

        files = [f"shard-{i:05d}" for i in range(23)]
        for n in range(1, 8):
            slices = [shard_for_host(h, n, files) for h in range(n)]
            flat = [f for s in slices for f in s]
            assert len(flat) == len(set(flat)) == len(files), n
            assert set(flat) == set(files), n

    def test_resize_keeps_the_invariant_at_every_m(self):
        from deep_vision_tpu.data.service import shard_for_host

        files = [f"shard-{i:05d}" for i in range(17)]
        for n in (2, 3, 5):
            for m in (1, 2, 3, 4, 6):
                if m == n:
                    continue
                # world resized N -> M: the NEW assignment must stand on
                # its own — disjoint and covering with no reference to
                # the old generation's slices
                new = [shard_for_host(h, m, files) for h in range(m)]
                flat = [f for s in new for f in s]
                assert sorted(flat) == sorted(files), (n, m)

    def test_index_form_matches_multihost_contract(self):
        from deep_vision_tpu.data.service import shard_for_host

        assert shard_for_host(1, 2) == (1, 2)
        with pytest.raises(ValueError):
            shard_for_host(2, 2)
        with pytest.raises(ValueError):
            shard_for_host(0, 0)


# -- DataLoaderState across a resize -------------------------------------------

class TestSnapshotAcrossResize:
    def _loader(self, host_shard):
        from deep_vision_tpu.data.pipeline import DataLoader

        data = [{"x": float(i)} for i in range(32)]
        return DataLoader(data, batch_size=4, seed=7, host_shard=host_shard)

    def test_snapshot_refuses_restore_at_different_world(self):
        from deep_vision_tpu.data.snapshot import SnapshotMismatch

        a = self._loader((0, 3))
        a.enable_snapshots()
        state = a.state_dict()
        # same world restores; a resized world refuses LOUDLY
        self._loader((0, 3)).load_state_dict(state)
        with pytest.raises(SnapshotMismatch):
            self._loader((0, 2)).load_state_dict(state)
        with pytest.raises(SnapshotMismatch):
            self._loader((1, 3)).load_state_dict(state)

    def test_fingerprint_includes_host_shard_slice(self):
        from deep_vision_tpu.data.snapshot import fingerprint

        data = [{"x": 1.0}]
        base = fingerprint(data, 4, 0)
        assert fingerprint(data, 4, 0, host_shard=(0, 3)) != base
        assert fingerprint(data, 4, 0, host_shard=(0, 3)) != \
            fingerprint(data, 4, 0, host_shard=(0, 2))
        assert fingerprint(data, 4, 0, host_shard=(0, 3)) == \
            fingerprint(data, 4, 0, host_shard=(0, 3))


# -- deadline-bounded collectives (the no-unbounded-block contract) ------------

class TestBoundedCollectives:
    def test_blocked_collective_raises_typed_host_lost(self):
        from deep_vision_tpu.parallel.multihost import _bounded_collective

        with pytest.raises(HostLostError) as ei:
            _bounded_collective(lambda: time.sleep(60), "stuck",
                                deadline_s=0.2)
        assert "deadline" in str(ei.value)

    def test_collective_errors_propagate_unwrapped(self):
        from deep_vision_tpu.parallel.multihost import _bounded_collective

        with pytest.raises(ValueError):
            _bounded_collective(
                lambda: (_ for _ in ()).throw(ValueError("x")).__next__(),
                "err", deadline_s=5.0)

    def test_sync_and_agree_route_through_rendezvous(self, tmp_path):
        from deep_vision_tpu.parallel import multihost as mh

        out, errs = join_world(str(tmp_path), ["a", "b"])
        assert not errs
        res = {}

        def run(h, flag):
            r, v = out[h]
            mh_view = v  # each thread installs its own world: module
            # state is per-process, so serialize via distinct names
            res[h] = r.agree("preempt", flag, timeout_s=10)

        ts = [threading.Thread(target=run, args=(h, h == "a"))
              for h in ("a", "b")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(15)
        assert res == {"a": True, "b": True}
        # the module-level overlay: install one side and verify the
        # lease-checked path raises on a dead peer instead of hanging
        ra, va = out["a"]
        out["b"][0]._hb_stop.set()
        time.sleep(4 * FAST["heartbeat_s"])
        try:
            mh.install_world(va, ra)
            with pytest.raises(HostLostError):
                mh.sync_hosts("post-death", deadline_s=10)
            with pytest.raises(HostLostError):
                mh.agree_flag(False, deadline_s=10)
        finally:
            mh.clear_world()
        ra.leave()


# -- HostSupervisor + Trainer.fit ----------------------------------------------

class TestHostSupervisor:
    def test_handle_loss_journals_the_full_trail_exactly_once(
            self, tmp_path):
        out, errs = join_world(str(tmp_path), ["a", "b"])
        assert not errs
        ra = out["a"][0]
        out["b"][0]._hb_stop.set()
        time.sleep(4 * FAST["heartbeat_s"])
        j = FakeJournal()
        sup = HostSupervisor(ra, journal=j, resume_step_fn=lambda: 42)
        with pytest.raises(HostLostError) as ei:
            ra.check()
        view = sup.handle_loss(ei.value)
        assert view.generation == 1 and view.hosts == ("a",)
        lost = j.of("host_lost")
        assert len(lost) == 1 and lost[0]["host"] == "b"
        assert lost[0]["generation"] == 0
        assert lost[0]["lease_gap_s"] > 0
        resized = j.of("world_resized")
        assert len(resized) == 1
        # the goodput plane's duration stamp rides along; its value is
        # wall-clock, so pin presence and shape, not the number
        wait = resized[0].pop("rendezvous_wait_s")
        assert wait >= 0
        assert resized == [{"event": "world_resized", "from": 2, "to": 1,
                            "generation": 1, "resume_step": 42}]
        rs = j.of("data_reshard")
        assert len(rs) == 1 and rs[0]["num_shards"] == 1
        # second detector parks instead of double-resizing: claim is spent
        assert sup._claim() is False
        ra.leave()

    def test_failed_resize_releases_the_claim_for_the_next_detector(
            self, tmp_path, monkeypatch):
        # the winner's resize failing must NOT leave the claim latched:
        # a parked loser with no active winner would be the indefinite
        # hang this module exists to remove
        out, errs = join_world(str(tmp_path), ["a", "b"])
        assert not errs
        ra = out["a"][0]
        out["b"][0]._hb_stop.set()
        time.sleep(4 * FAST["heartbeat_s"])
        sup = HostSupervisor(ra, journal=FakeJournal())
        monkeypatch.setattr(sup, "resize",
                            lambda **kw: (_ for _ in ()).throw(
                                RendezvousTimeout("record never appeared")))
        with pytest.raises(HostLostError) as ei:
            ra.check()
        with pytest.raises(RendezvousTimeout):
            sup.handle_loss(ei.value)
        assert sup._claim() is True  # released: the next detector retries
        ra.leave()

    def test_bounded_fetch_returns_value_and_raises_on_death(self, tmp_path):
        out, errs = join_world(str(tmp_path), ["a", "b"])
        assert not errs
        ra = out["a"][0]
        sup = HostSupervisor(ra, journal=FakeJournal(), fence_poll_s=0.05)
        assert sup.bounded_fetch(lambda: 7) == 7
        out["b"][0]._hb_stop.set()
        time.sleep(4 * FAST["heartbeat_s"])
        with pytest.raises(HostLostError):
            sup.bounded_fetch(lambda: time.sleep(60))
        ra.leave()

    def test_trainer_fit_rides_host_loss_to_world_resized(self, tmp_path):
        """fit() supervision end to end (single jax process, real
        rendezvous, one ghost peer): the dead host surfaces through the
        preemption-consensus barrier as HostLostError, fit journals
        host_lost + world_resized + data_reshard and raises the typed
        WorldResized carrying the g+1 view."""
        import jax.numpy as jnp
        import numpy as np

        from deep_vision_tpu.losses import classification_loss_fn
        from deep_vision_tpu.models import get_model
        from deep_vision_tpu.parallel import multihost as mh
        from deep_vision_tpu.train import Trainer, build_optimizer

        out, errs = join_world(str(tmp_path / "rdzv"), ["a", "b"])
        assert not errs
        ra, va = out["a"]
        out["b"][0]._hb_stop.set()  # the peer dies before the first poll
        j = FakeJournal()
        try:
            mh.install_world(va, ra)
            sup = HostSupervisor(ra, journal=j)
            rng = np.random.RandomState(0)
            images = rng.rand(32, 32, 32, 1).astype(np.float32)
            labels = rng.randint(0, 4, size=32).astype(np.int32)
            trainer = Trainer(
                get_model("lenet5", num_classes=4),
                build_optimizer("adam", 1e-3), classification_loss_fn,
                sample_input=jnp.zeros((8, 32, 32, 1)),
                journal=j, host_supervisor=sup,
            )

            def data():
                for i in range(4):
                    yield {"image": images[i * 8:(i + 1) * 8],
                           "label": labels[i * 8:(i + 1) * 8]}

            with pytest.raises(WorldResized) as ei:
                trainer.fit(data, epochs=2, preemption_poll_every=2)
            assert ei.value.view.generation == 1
            assert ei.value.view.hosts == ("a",)
            assert [r["host"] for r in j.of("host_lost")] == ["b"]
            resized = j.of("world_resized")
            assert len(resized) == 1
            assert (resized[0]["from"], resized[0]["to"]) == (2, 1)
            # no checkpoint manager: the honest resume_step is -1
            assert resized[0]["resume_step"] == -1
            assert len(j.of("data_reshard")) == 1
        finally:
            mh.clear_world()
            ra.leave()

    def test_trainer_pins_world_shard_into_unsharded_loader(self, tmp_path):
        """A production loader built without host_shard would fingerprint
        identically across a resize — the Trainer stamps the world's
        slice at attach so the SnapshotMismatch refusal can actually
        fire."""
        import jax.numpy as jnp

        from deep_vision_tpu.data.pipeline import DataLoader
        from deep_vision_tpu.losses import classification_loss_fn
        from deep_vision_tpu.models import get_model
        from deep_vision_tpu.train import Trainer, build_optimizer

        out, errs = join_world(str(tmp_path), ["a", "b"])
        assert not errs
        ra, va = out["a"]
        loader = DataLoader([{"x": 1.0}] * 8, batch_size=4)
        assert loader.host_shard is None
        Trainer(
            get_model("lenet5", num_classes=4),
            build_optimizer("adam", 1e-3), classification_loss_fn,
            sample_input=jnp.zeros((4, 32, 32, 1)),
            host_supervisor=HostSupervisor(ra, journal=FakeJournal()),
            data_loader=loader,
        )
        assert loader.host_shard == va.shard() == (0, 2)
        for r, _ in out.values():
            r.leave()

    def test_attach_reenters_a_written_generation(self, tmp_path, monkeypatch):
        out, errs = join_world(str(tmp_path), ["a", "b"])
        assert not errs
        # both resize after b... no: simulate the re-exec re-entry — a
        # FRESH Rendezvous instance attaches to the generation the env
        # names, as the exec'd process would
        monkeypatch.setenv(ENV_GENERATION, "0")
        fresh = {}

        def run(h):
            r = Rendezvous(str(tmp_path), h, **FAST)
            fresh[h] = r.attach(timeout_s=10)

        # the original members keep heartbeating (their leases are what
        # the fresh instances' ack barrier sweeps)
        ts = [threading.Thread(target=run, args=(h,)) for h in ("a", "b")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(15)
        assert fresh["a"].hosts == ("a", "b")
        assert fresh["a"].generation == 0
        assert fresh["b"].rank == 1
        for r, _ in out.values():
            r.leave()


# -- journal schemas + obs surfaces --------------------------------------------

class TestSchemas:
    def _check(self, rows, strict=True):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "check_journal", os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "tools", "check_journal.py"))
        cj = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(cj)
        import tempfile

        base = {"ts": 1.0, "run_id": "t"}
        with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                         delete=False) as f:
            for r in rows:
                f.write(json.dumps({**base, **r}) + "\n")
            f.write(json.dumps({**base, "event": "exit",
                                "status": "clean_exit"}) + "\n")
            path = f.name
        try:
            return cj.check_journal(path, strict=strict)
        finally:
            os.unlink(path)

    def test_membership_events_accepted(self):
        assert self._check([
            {"event": "host_lost", "host": "h1", "generation": 0,
             "lease_gap_s": 2.5},
            {"event": "host_joined", "host": "h3", "generation": 2},
            {"event": "world_resized", "from": 3, "to": 2, "generation": 1,
             "resume_step": 8},
            {"event": "data_reshard", "generation": 1, "from": 3, "to": 2,
             "shard_index": 0, "num_shards": 2},
        ]) == []

    def test_membership_events_rejected_on_bad_types(self):
        assert self._check([{"event": "host_lost", "host": 1,
                             "generation": 0}])
        assert self._check([{"event": "host_lost", "host": "h1",
                             "generation": "zero"}])
        assert self._check([{"event": "world_resized", "from": 3, "to": 2,
                             "generation": 1}])  # resume_step missing
        assert self._check([{"event": "world_resized", "from": 3, "to": 0,
                             "generation": 1, "resume_step": -1}])
        assert self._check([{"event": "data_reshard", "generation": 1,
                             "from": "three", "to": 2}])

    def test_event_names_match_supervisor_emissions(self, tmp_path):
        """The schema enum and the emitter cannot drift: every event the
        HostSupervisor writes must validate --strict."""
        out, errs = join_world(str(tmp_path), ["a", "b"])
        assert not errs
        ra = out["a"][0]
        out["b"][0]._hb_stop.set()
        time.sleep(4 * FAST["heartbeat_s"])
        j = FakeJournal()
        sup = HostSupervisor(ra, journal=j, resume_step_fn=lambda: 3)
        with pytest.raises(HostLostError) as ei:
            ra.check()
        sup.handle_loss(ei.value)
        sup.on_host_joined("c", 1)
        assert self._check(j.rows) == []
        ra.leave()


class TestObsSurfaces:
    def test_obs_report_membership_section(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "obs_report", os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "tools", "obs_report.py"))
        rep = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(rep)
        base = {"ts": 1.0, "run_id": "r"}
        events = [
            {**base, "event": "host_lost", "host": "h1", "generation": 0,
             "lease_gap_s": 2.1},
            {**base, "event": "world_resized", "from": 3, "to": 2,
             "generation": 1, "resume_step": 8},
            {**base, "event": "data_reshard", "generation": 1, "from": 3,
             "to": 2, "shard_index": 0, "num_shards": 2},
            {**base, "event": "exit", "status": "clean_exit"},
        ]
        summary = rep.summarize_run(events)
        assert summary["membership"]["generations"][0]["resume_step"] == 8
        text = rep.render(summary)
        assert "host_lost h1" in text
        assert "world 3 -> 2" in text
        assert "resume step 8" in text
        assert "data_reshard" in text
        # no membership events -> no section, report byte-unchanged
        plain = rep.summarize_run([{**base, "event": "exit",
                                    "status": "clean_exit"}])
        assert "membership" not in plain

    def test_merge_tolerates_a_dead_hosts_partial_journal(self, tmp_path):
        from deep_vision_tpu.obs.merge import merge_journal_files

        good = tmp_path / "run.jsonl.p0"
        rows = [{"event": "step", "ts": 1.0, "run_id": "r", "step": 1,
                 "step_time_ms": 10.0}]
        good.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        torn = tmp_path / "run.jsonl.p1"
        torn.write_text(json.dumps(
            {"event": "step", "ts": 1.0, "run_id": "r", "step": 1,
             "step_time_ms": 11.0}) + "\n" + '{"event": "ste')
        missing = str(tmp_path / "run.jsonl.p2")  # SIGKILLed pre-flush
        out = str(tmp_path / "merged.jsonl")
        summary = merge_journal_files([str(good), str(torn), missing], out)
        assert summary["unreadable"] == [missing]
        assert summary["hosts"] == [0, 1]
        header = json.loads(open(out).readline())
        assert header["unreadable_sources"] == [missing]


# -- preflight ------------------------------------------------------------------

class TestPreflightRendezvous:
    def test_skewed_joiner_fails_as_version_skew(self, tmp_path):
        from deep_vision_tpu.tools.preflight import check_rendezvous

        incumbent = Rendezvous(str(tmp_path), "fleet-0", **FAST,
                               client_version="jax 0.4.37, jaxlib 0.4.36",
                               platform_version="libtpu 2024.1")
        incumbent.start_heartbeat()
        r = check_rendezvous(
            2, str(tmp_path), host_id="joiner", budget_s=20.0,
            versions={"client_version": "jax 0.4.30, jaxlib 0.4.30",
                      "platform_version": "libtpu 2023.9"})
        assert not r.ok
        assert r.kind == "version_skew"
        incumbent.leave()

    def test_compatible_world_assembles_and_probe_leaves(self, tmp_path):
        from deep_vision_tpu.tools.preflight import check_rendezvous

        versions = {"client_version": "v1", "platform_version": "p1"}
        results = {}

        def probe(name):
            results[name] = check_rendezvous(
                2, str(tmp_path), host_id=name, budget_s=20.0,
                versions=versions)

        ts = [threading.Thread(target=probe, args=(n,))
              for n in ("pf-a", "pf-b")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert all(r.ok for r in results.values()), results
        assert "world of 2" in results["pf-a"].detail
        # probes left: no member records squat the slots the real run
        # is about to claim
        assert os.listdir(tmp_path / "members") == []

    def test_probe_leftovers_never_squat_the_dir(self, tmp_path):
        """A preflight round leaves a stale generation record; the REAL
        run (same dir, fresh member ids or not) must still assemble —
        at the next generation — instead of being refused as evicted."""
        from deep_vision_tpu.tools.preflight import check_rendezvous

        versions = {"client_version": "v1"}
        results = {}

        def probe(name):
            results[name] = check_rendezvous(
                2, str(tmp_path), host_id=name, budget_s=20.0,
                versions=versions)

        for round_no in (0, 1):  # second round = the rerun case
            ts = [threading.Thread(target=probe, args=(f"r{round_no}-{i}",))
                  for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(30)
        assert all(r.ok for r in results.values()), results
        # and the real run after both probe rounds:
        out, errs = join_world(str(tmp_path), ["real-a", "real-b"],
                               client_version="v1")
        assert not errs, errs
        assert all(v.generation == 2 for _, v in out.values())
        for r, _ in out.values():
            r.leave()

    def test_never_assembles_fails_as_timeout(self, tmp_path):
        from deep_vision_tpu.tools.preflight import check_rendezvous

        r = check_rendezvous(3, str(tmp_path), host_id="alone",
                             budget_s=0.5, versions={})
        assert not r.ok and r.kind == "timeout"
