"""Benchmark: ResNet-50 training throughput (images/sec) on the local chip(s).

Default mode runs the framework's real jitted train step (forward + loss +
backward + SGD update + BN stat update) on the flagship model with synthetic
ImageNet-shaped data in bfloat16 compute (fp32 params), and prints ONE JSON
line:

    {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N}

Baseline: the reference repo publishes no throughput for its classifiers (its
only perf number is YOLOv3 epoch time, BASELINE.md); the driver's north star
is ">= 0.9x A100x8 images/sec" for ResNet-50 (BASELINE.json). We normalize
per chip: an A100 sustains ~2900 images/sec on ResNet-50/224 mixed-precision
training (MLPerf-class recipe), so the per-chip target is 0.9 * 2900 = 2610
and vs_baseline = value_per_chip / 2610.

`value` is the WALL-CLOCK rate (all host-side overhead included) so the
headline is comparable across rounds and to BASELINE.json; the
profiler-derived device-time rate is reported alongside under
`device_images_per_sec_per_chip`. MFU and HBM traffic per step are
reported from XLA's post-fusion cost analysis so the "HBM-bound"
characterization is a number, not a sentence.

Wall-vs-device accounting (measured, artifacts/dispatch_r04.json): on this
rig the ONLY non-device cost is a constant ~118 ms PER HOST SYNCHRONIZATION
(the scalar fetch that closes a timed window) — the pure round trip of a
trivial kernel through the relay is the same ~120 ms. Dispatch enqueues are
async (~7 ms) and the device executes steps back-to-back (median
inter-module gap 6 us), so wall fits wall = 118 + 97.9*N ms over window
length N to <6 ms residual. The round-3 story that the gap was a
per-dispatch "relay turnaround" was wrong — the observed 5.5 ms/step was
118 ms amortized over r3's 20-step windows. Timed windows here are
TIMED_STEPS=600 steps long, amortizing the sync to ~0.2 ms/step, the same
way a real training loop (which syncs for logging every few hundred steps)
does; a real v5e host also pays its (much smaller) sync cost only at the
same boundaries.

Resilience: the timing loop retries transient runtime/transport failures
(the round-2 driver run died to a single tunnel hiccup, `BENCH_r02.json`)
by rebuilding the jitted step and replaying the window; the JSON line is
ALWAYS emitted, degraded if necessary, with an `error` field. The
rebuild-replay bookkeeping — retry budget, failure classification,
jittered backoff, typed backend_lost/backend_recovered journal events —
is the shared `resilience.elastic.BackendSupervisor` (this file's
bespoke loop was its prototype; the Trainer now drives the same object);
only the control flow stays local because it is bench-specific (donated
buffers die with the failure, so windows replay on a rebuilt step). Two hard
wall-clock guards make that promise hold even against a HUNG (not erroring)
backend — the round-4 failure mode, where a dead relay tunnel blocks the
main thread in socket recv and no exception ever fires (`BENCH_r04.json`:
rc=124, no output): a threaded liveness probe must complete a trivial
device op within BENCH_INIT_BUDGET_S (default 180 s) before any real work
starts, and a watchdog thread force-emits the degraded JSON line and exits
0 at BENCH_BUDGET_S (default 1500 s) no matter where the main thread is
stuck. A healthy fresh-compile run finishes in ~6 min; both budgets are
env-overridable.

`--data host` / `--data fused` instead benchmark the REAL input pipeline
(SURVEY §7 hard part #1): sharded records -> JPEG decode -> augment -> host
batches (`host`), plus space-to-depth + device_put onto the chip (`fused`),
over a self-generated JPEG record fixture. The number is reported per host
CPU core (this VM has one; the 224-vCPU host of a real v5e-8 slice scales
the pipeline linearly with cores via DataLoader(num_procs=...)), with
vs_baseline = per_core / (8 * 2610 / 224) — the per-core rate at which a
full v5e-8 host (224 vCPUs) keeps all 8 chips fed.
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from deep_vision_tpu.resilience import RetryPolicy
from deep_vision_tpu.resilience.elastic import BackendSupervisor

A100_IMG_PER_SEC = 2900.0
TARGET_PER_CHIP = 0.9 * A100_IMG_PER_SEC

BATCH_PER_CHIP = 128  # the measured per-chip optimum: 46.3 ms/step device
                      # = 2764 img/s vs 97.9 ms = 2615 at 256 (the whole
                      # curve: artifacts/batch_scaling_r04.json; batch 512
                      # crosses the HBM-capacity line and rematerializes)
IMAGE_SIZE = 224
WARMUP_STEPS = 5
TIMED_STEPS = 600  # steps per timed window. Long windows amortize the
                   # ~118 ms per-host-sync relay latency (measured:
                   # artifacts/dispatch_r04.json) to ~0.2 ms/step, as any
                   # real training loop does between logging boundaries.
WINDOWS = 3  # report the MEDIAN window: robust to tunnel jitter without
             # inflating the metric the way a best-of-N min would
MAX_RETRIES = 5  # rebuild-and-replay budget for transient tunnel failures

# Hard wall-clock budgets (seconds, env-overridable). A dead tunnel HANGS
# rather than raising, so exception-based retries alone cannot bound the
# run; these can. Healthy timings for scale: fresh-shape compile ~4 min,
# warmup + 3x600-step windows ~2 min, liveness round trip ~120 ms.
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "1500"))
INIT_BUDGET_S = float(os.environ.get("BENCH_INIT_BUDGET_S", "180"))
# cooperative early-stop margins, scaled down with tiny (test) budgets: how
# close to the watchdog deadline it is still worth starting another timed
# window / a device trace window
_STOP_MARGIN_S = min(120.0, 0.1 * BUDGET_S)
_TRACE_MARGIN_S = min(90.0, 0.075 * BUDGET_S)
# starting a REBUILD needs room for a fresh-shape compile (~4 min on this
# rig): rebuilding with less than this left would let the watchdog fire
# mid-compile and lose the pre-failure windows' degraded median
_REBUILD_MARGIN_S = min(330.0, 0.22 * BUDGET_S)

_DEADLINE = None  # monotonic; set when the watchdog starts
_EMIT_LOCK = threading.Lock()
_EMITTED = False
_LAST_STAGE = "start"
_WINDOWS_DONE = 0


def _emit(result: dict) -> bool:
    """Print the one contract JSON line, exactly once process-wide.

    Both the normal completion path and the watchdog call this; whichever
    arrives first wins, so the driver can never see two JSON lines (or
    zero)."""
    global _EMITTED
    with _EMIT_LOCK:
        if _EMITTED:
            return False
        _EMITTED = True
        # stamp the environment fingerprint the perf ledger keys on
        # (tools/perf_gate.py): a number without its environment is not
        # comparable, and the stamp must ride the SAME line the driver
        # captures — best-effort, a bench must never die to bookkeeping
        try:
            from tools.perf_gate import default_env, env_key

            result.setdefault("env", default_env())
            result.setdefault("env_key", env_key(result["env"]))
        except Exception:
            pass
        # print under the lock: if the winner released first and was then
        # descheduled before printing, the loser's path could reach
        # _hard_exit and kill the process with ZERO lines emitted
        print(json.dumps(result), flush=True)
        # journal AFTER the stdout contract line (best-effort, never
        # raises): emitting here — not per run mode — covers train, sweep,
        # data AND the watchdog's degraded line with one code path
        _journal_result(result)
    return True


def _remaining() -> float:
    return math.inf if _DEADLINE is None else _DEADLINE - time.monotonic()


def _hard_exit(code: int = 0) -> None:
    """Flush, reap worker children, then os._exit.

    os._exit skips multiprocessing's atexit cleanup, and surviving decode
    workers hold an inherited stdout fd — a driver reading the pipe to EOF
    would block on them past its timeout even with the parent gone. So the
    children are terminated explicitly first."""
    try:
        sys.stdout.flush()
        sys.stderr.flush()
    except Exception:
        pass
    try:
        import multiprocessing

        for p in multiprocessing.active_children():
            p.terminate()
        for p in multiprocessing.active_children():
            p.join(timeout=2.0)
            if p.is_alive():
                p.kill()
    except Exception:
        pass
    os._exit(code)


def _start_watchdog(result: dict) -> None:
    """Arm the BENCH_BUDGET_S guard: at the deadline, emit `result` (plus a
    budget-exhausted error and the last logged stage) and exit 0.

    os._exit, not sys.exit: the main thread may be unrecoverably blocked in
    a backend socket recv, and a hung jax client can also wedge interpreter
    teardown — the driver must see rc=0 and one parseable line regardless.
    """
    global _DEADLINE
    _DEADLINE = time.monotonic() + BUDGET_S

    def bite():
        while time.monotonic() < _DEADLINE:
            time.sleep(min(1.0, max(0.05, _DEADLINE - time.monotonic())))
        try:  # snapshot: the main thread may be mutating `result` right now
            payload = dict(result)
            errors = list(payload.get("errors", []))
        except RuntimeError:
            payload = {"metric": result.get("metric", "unknown"),
                       "value": 0.0, "vs_baseline": 0.0}
            errors = []
        errors.append(
            f"wall-clock budget exhausted ({BUDGET_S:.0f}s); "
            f"last stage: {_LAST_STAGE}"
        )
        payload["errors"] = errors[-5:]
        payload.setdefault("windows_completed", _WINDOWS_DONE)
        _emit(payload)
        _hard_exit(0)

    threading.Thread(target=bite, daemon=True, name="bench-watchdog").start()


def _backend_alive(budget_s: float, probe=None):
    """(ok, error) — does a trivial device op complete within budget_s?

    Thin wrapper over the shared threaded probe
    (resilience.elastic.backend_alive — a dead relay BLOCKS in socket
    recv, so only a join timeout can see it; the same probe gates
    tools/preflight.py). The orphaned thread stays blocked and is
    daemon-irrelevant because degraded exits go through os._exit."""
    from deep_vision_tpu.resilience.elastic import backend_alive

    if probe is None and os.environ.get("BENCH_SIMULATE_DEAD"):
        # rehearsal hook: behave exactly like a dead relay (block, don't
        # raise) so the degraded path can be exercised on a healthy machine
        def probe():
            return time.sleep(7 * 24 * 3600)
    return backend_alive(budget_s, probe=probe)

# bf16 peak of the chips this bench is expected to meet; device_kind prefix
# match, first hit wins, conservative default otherwise.
PEAK_BF16_FLOPS = (
    ("TPU v5 lite", 197e12),  # v5e
    ("TPU v5e", 197e12),
    ("TPU v5p", 459e12),
    ("TPU v4", 275e12),
    ("TPU v6", 918e12),  # trillium
)
# Analytic fallback when XLA cost analysis is unavailable: ResNet-50/224
# forward is ~4.09 GMACs/image (torchvision table); MFU convention counts a
# MAC as 2 flops and training (fwd + bwd wrt activations + bwd wrt weights)
# as 3x forward.
RESNET50_TRAIN_FLOPS_PER_IMAGE = 2 * 4.089e9 * 3


FIXTURE_DIR = "/tmp/deep_vision_tpu_bench_records"
# per-core feed target: 8 chips x 2610 img/s spread over a v5e-8 host's 224
# vCPUs (GCP ct5lp-hightpu-8t machine shape)
DATA_TARGET_PER_CORE = 8 * 2610.0 / 224.0


def _ensure_fixture(n_shards: int = 4, per_shard: int = 256) -> str:
    """Self-generated JPEG record shards (~45KB/img, ImageNet-like sizes)."""
    import cv2

    from deep_vision_tpu.data.example_codec import encode_example
    from deep_vision_tpu.data.records import RecordWriter

    if os.path.isdir(FIXTURE_DIR) and len(os.listdir(FIXTURE_DIR)) == n_shards:
        return FIXTURE_DIR
    os.makedirs(FIXTURE_DIR, exist_ok=True)
    rng = np.random.RandomState(0)
    for s in range(n_shards):
        path = os.path.join(FIXTURE_DIR, f"train-{s:05d}")
        # write-then-rename: a Ctrl-C'd prior run must not leave a truncated
        # shard that the count-based reuse check above would accept
        tmp = path + ".tmp"
        with RecordWriter(tmp) as w:
            for _ in range(per_shard):
                img = (rng.rand(375, 500, 3) * 60 + 90).astype(np.uint8)
                img += np.arange(500, dtype=np.uint8)[None, :, None] // 4
                ok, enc = cv2.imencode(
                    ".jpg", img, [cv2.IMWRITE_JPEG_QUALITY, 90]
                )
                assert ok
                w.write(encode_example({
                    "image/encoded": [enc.tobytes()],
                    "image/class/label": [int(rng.randint(1, 1001))],
                }))
        os.replace(tmp, path)
    return FIXTURE_DIR


def data_main(mode: str, num_procs: int) -> None:
    """Input-pipeline benchmark: the full ImageNet train chain."""
    from deep_vision_tpu.data import Compose, DataLoader, RecordDataset
    from deep_vision_tpu.data import transforms as T

    _ensure_fixture()
    ds = RecordDataset(FIXTURE_DIR + "/*", "imagenet", shuffle_shards=True)
    chain = Compose([
        T.Rescale(256), T.RandomHorizontalFlip(), T.RandomCrop(IMAGE_SIZE),
        T.ColorJitter(0.4, 0.4, 0.4),
        T.ToFloatNormalize(expand_gray_to_rgb=True),
        T.SpaceToDepth(),  # flagship config's host half of the s2d stem
    ])
    dl = DataLoader(ds, BATCH_PER_CHIP, chain, shuffle=True,
                    shuffle_buffer=1024, num_workers=8, num_procs=num_procs,
                    drop_remainder=True)
    if mode == "fused":
        from deep_vision_tpu.parallel.mesh import create_mesh, data_sharding

        mesh = create_mesh()
        put = lambda b: jax.device_put(
            jnp.asarray(b["image"], jnp.bfloat16),
            data_sharding(mesh, 4),
        )
    n_cores = os.cpu_count() or 1
    n = 0
    t0 = time.perf_counter()
    for batch in dl:
        if mode == "fused":
            jax.block_until_ready(put(batch))
        n += len(batch["image"])
    dt = time.perf_counter() - t0
    per_core = n / dt / n_cores
    print(
        f"bench-data: {mode} {n} imgs in {dt:.1f}s on {n_cores} core(s), "
        f"num_procs={num_procs}",
        file=sys.stderr,
    )
    _emit({
        "metric": f"imagenet_pipeline_{mode}_images_per_sec_per_core",
        "value": round(per_core, 1),
        "unit": "images/sec/core",
        "vs_baseline": round(per_core / DATA_TARGET_PER_CORE, 3),
    })


def _log(msg: str) -> None:
    global _LAST_STAGE
    _LAST_STAGE = msg  # the watchdog's degraded JSON names the stuck stage
    print(f"bench: {msg}", file=sys.stderr, flush=True)


# steps per timed scaling window; small by default — four sub-mesh builds
# each pay a compile, and the signal is the RATIO between rows, which
# stabilizes in a handful of steps
MULTICHIP_STEPS = int(os.environ.get("BENCH_MULTICHIP_STEPS", "16"))
MULTICHIP_BATCH = int(os.environ.get("BENCH_MULTICHIP_BATCH", "16"))


def multichip_result_stub() -> dict:
    return {"metric": "multichip_scaling", "value": 0.0,
            "unit": "efficiency_fraction", "rows": []}


def multichip_main(result: dict) -> None:
    """MULTICHIP mode: the scaling-efficiency block that replaces the
    dryrun's bare `loss=OK` as the multi-chip artifact's payload.

    A table-sharded (parallel/shardmap.py) slim-flagship train step is
    timed at data={1,2,4,8} sub-meshes of the available devices
    (deep_vision_tpu/tools/scaling.py); the one contract JSON line
    carries throughput + per-device examples/s per row and the
    efficiency fraction vs the 1-device baseline as the headline value
    — also journaled as a typed `bench` event under --journal, so
    obs_report renders the curve and MULTICHIP_r0N rounds diff as
    numbers."""
    from deep_vision_tpu.tools.scaling import (
        format_rows,
        measure_scaling,
        scaling_result,
    )

    _log(f"multichip scaling: {len(jax.devices())} devices, "
         f"{MULTICHIP_STEPS} steps x batch {MULTICHIP_BATCH}/device")
    try:
        rows = measure_scaling(batch_per_device=MULTICHIP_BATCH,
                               steps=MULTICHIP_STEPS)
        print(format_rows(rows), file=sys.stderr, flush=True)
        result.update(scaling_result(rows))
    except KeyboardInterrupt:
        raise
    except Exception as e:
        result["errors"] = result.get("errors", []) + [
            f"{type(e).__name__}: {e}"]
        _log(f"fatal: {type(e).__name__}: {e}")
    finally:
        _emit(result)


def _cold_start_fields() -> dict:
    """cache-cold vs cache-warm cold start, measured in the SAME run on
    the same probe computation (core/excache.py round trip):

      warmup_compile_ms   the compiler's bill — lower + XLA compile +
                          store into a fresh executable cache
      cold_start_ms       what a restarted process pays over a POPULATED
                          cache — lower + deserialize, zero compiles

    The ratio is the recovery-time-objective win the persistent
    executable cache buys serve warmup / elastic rebuild / host re-exec.
    """
    import shutil
    import tempfile

    from deep_vision_tpu.core.excache import ExecutableCache
    from deep_vision_tpu.obs.registry import Registry

    d = tempfile.mkdtemp(prefix="bench_excache_")
    try:
        cache = ExecutableCache(d, registry=Registry())
        f = jax.jit(lambda v, x: jnp.tanh(x @ v) @ v)
        v = jnp.ones((256, 256), jnp.float32)
        spec = jax.ShapeDtypeStruct((64, 256), jnp.float32)
        t0 = time.perf_counter()
        compiled, src = cache.get_or_compile(
            f.lower(v, spec), name="bench/coldstart")
        compile_ms = (time.perf_counter() - t0) * 1e3
        t1 = time.perf_counter()
        cached, src2 = cache.get_or_compile(
            f.lower(v, spec), name="bench/coldstart")
        cached_ms = (time.perf_counter() - t1) * 1e3
        if src != "compiled" or src2 != "cache":
            # a backend that can't serialize executables: report the
            # honest compile number and no fake cached one
            return {"warmup_compile_ms": round(compile_ms, 1)}
        return {"warmup_compile_ms": round(compile_ms, 1),
                "cold_start_ms": round(cached_ms, 1)}
    finally:
        shutil.rmtree(d, ignore_errors=True)


def make_train_parts(batch_per_chip: int, stem: str = "s2d"):
    """(train_step_fn, state, batch, batch_size, n_chips, devices): the
    UNJITTED flagship train step + freshly staged inputs.

    Shared by build_bench and the perf probes (tools/layout_probe.py,
    tools/bench_ablate.py) so every measurement times the same program.
    Everything device-resident is created from host-side seeds so a rebuild
    is bit-equivalent.
    """
    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.losses.classification import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.parallel.mesh import create_mesh, data_sharding, replicated
    from deep_vision_tpu.train.optimizers import build_optimizer

    devices = jax.devices()
    n_chips = len(devices)
    mesh = create_mesh(devices=devices)
    batch_size = batch_per_chip * n_chips

    # space-to-depth stem (models/resnet.py SpaceToDepthStem): the host
    # pipeline ships (H/2, W/2, 12) images; the stem conv is math-identical
    # to 7x7/s2 but MXU-efficient. Input staged in bf16, as the real
    # pipeline does (uint8 decode -> normalize -> bf16 cast on host).
    model = get_model("resnet50", num_classes=1000, dtype=jnp.bfloat16,
                      stem=stem)
    tx = build_optimizer("sgd", learning_rate=0.1, momentum=0.9,
                         weight_decay=1e-4)
    if stem == "s2d":
        img_shape = (IMAGE_SIZE // 2, IMAGE_SIZE // 2, 12)
    else:
        img_shape = (IMAGE_SIZE, IMAGE_SIZE, 3)
    sample = jnp.ones((8, *img_shape), jnp.float32)
    state = create_train_state(model, tx, sample)
    state = jax.device_put(state, replicated(mesh))

    rng = np.random.RandomState(0)
    batch = {
        "image": rng.rand(batch_size, *img_shape)
        .astype(np.float32).astype(jnp.bfloat16),
        "label": rng.randint(0, 1000, size=(batch_size,)).astype(np.int32),
    }
    batch = {
        k: jax.device_put(v, data_sharding(mesh, v.ndim)) for k, v in batch.items()
    }

    def train_step(state, batch):
        step_rng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(params):
            variables = {"params": params, "batch_stats": state.batch_stats}
            outputs, new_model_state = state.apply_fn(
                variables,
                batch["image"],
                train=True,
                rngs={"dropout": step_rng},
                mutable=["batch_stats"],
            )
            loss, _ = classification_loss_fn(outputs, batch)
            return loss, new_model_state["batch_stats"]

        (loss, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        return state.apply_gradients(grads).replace(batch_stats=new_bs), loss

    return train_step, state, batch, batch_size, n_chips, devices


def build_bench(batch_per_chip: int, multistep: int):
    """(Re)build mesh, model state, synthetic batch and the jitted step.

    Called once at start and again after any transient runtime failure —
    a replay is bit-equivalent to the original attempt (make_train_parts).
    """
    (train_step, state, batch, batch_size, n_chips,
     devices) = make_train_parts(batch_per_chip)

    if multistep > 1:
        # K optimizer steps per dispatch: a lax.scan superstep. Quantifies
        # (and, on hosts where dispatch is the bottleneck, removes) the
        # per-dispatch turnaround cost.
        def superstep(state, batch):
            def body(s, _):
                s, loss = train_step(s, batch)
                return s, loss

            state, losses = jax.lax.scan(body, state, None, length=multistep)
            return state, losses[-1]

        fn = superstep
    else:
        fn = train_step

    # AOT-compile once: the SAME executable serves the timed windows and
    # cost_analysis() afterwards (a plain jit would recompile for the
    # post-run .lower().compile() — a duplicate multi-second compile)
    step = jax.jit(fn, donate_argnums=0).lower(state, batch).compile()

    return step, state, batch, batch_size, n_chips, devices


def _retry_policy() -> RetryPolicy:
    """The bench retry policy, built per call so a monkeypatched
    MAX_RETRIES (tests) is honored. retry_on=Exception: jax wraps tunnel
    failures in RuntimeError, and everything this loop runs is a replayable
    pure computation, so any Exception here is worth one more attempt."""
    # max_attempts counts the first try too: MAX_RETRIES retries on top
    return RetryPolicy(name="bench.window", max_attempts=MAX_RETRIES + 1,
                       base_delay_s=2.0, multiplier=2.0, max_delay_s=15.0,
                       jitter=0.25, retry_on=Exception)


def _make_supervisor() -> BackendSupervisor:
    """One BackendSupervisor per _timed_windows session: the rebuild-replay
    bookkeeping — backoff jitter RNG (ONE RNG, advancing per draw), typed
    backend_lost/backend_recovered journal events, flight-recorder
    breadcrumbs, clear_caches pacing — lives in a single object
    (resilience/elastic.py; this replaced the module-global _ACTIVE_POLICY
    shim, whose `or _retry_policy()` fallback could silently re-seed and
    re-draw the same "jittered" delay). retry_unclassified: a bench window
    is a replayable pure computation, so any Exception is worth one more
    attempt — except a version skew, which never heals mid-run."""
    return BackendSupervisor(policy=_retry_policy(), journal=_JOURNAL,
                             name="bench.window", retry_unclassified=True)


def _cost_analysis(step, multistep: int, batch_per_chip: int):
    """(flops_per_step_per_chip, bytes_per_step_per_chip, source).

    XLA's compiled cost analysis reports PER-DEVICE numbers under SPMD
    (verified: an 8-way sharded matmul reports 1/8 of the global flops), so
    everything here is per chip; divide by `batch_per_chip` — NOT the
    global batch — for per-image figures. Analytic fallback for flops,
    None for bytes, if unsupported. `step` is the AOT-compiled executable
    from build_bench."""
    try:
        ca = step.cost_analysis()
        if isinstance(ca, (list, tuple)):  # older jax returns [dict]
            ca = ca[0]
        flops = float(ca["flops"]) / multistep
        bytes_acc = ca.get("bytes accessed")
        bytes_acc = float(bytes_acc) / multistep if bytes_acc else None
        if flops > 0:
            return flops, bytes_acc, "xla_cost_analysis"
    except Exception as e:
        _log(f"cost analysis unavailable ({type(e).__name__}: {e}); "
             "using analytic flops")
    return RESNET50_TRAIN_FLOPS_PER_IMAGE * batch_per_chip, None, "analytic"


def _peak_flops(device_kind: str) -> float:
    for prefix, peak in PEAK_BF16_FLOPS:
        if device_kind.startswith(prefix):
            return peak
    return 197e12


def _timed_windows(batch_per_chip: int, multistep: int):
    """Run warmup + WINDOWS timed windows with transient-failure retry.

    Returns (per-step wall seconds list, step, state, batch, batch_size,
    n_chips, devices, errors). On a transient failure ALL windows are
    replayed on the rebuilt step: windows timed before the failure may have
    run on a degraded-but-not-yet-dead tunnel, and mixing them into the
    median would skew the headline (r3 advisor finding). Only if the retry
    budget exhausts with zero healthy-session windows do the pre-failure
    windows feed the median, flagged in `errors` as degraded.
    """
    dispatches = max(1, math.ceil(TIMED_STEPS / multistep))
    steps_per_window = dispatches * multistep
    sup = _make_supervisor()
    errors = []
    window_dts = []
    stale_dts = []  # pre-failure windows: degraded fallback only
    built = None
    last_good = None  # survives rebuild failures: completed windows stay
                      # attributed to a real (step, ..., devices) tuple
    attempt = 0
    recovered_noted = False
    global _WINDOWS_DONE
    while len(window_dts) < WINDOWS:
        margin = _STOP_MARGIN_S if built else _REBUILD_MARGIN_S
        if _remaining() < margin:
            # close enough to the watchdog that another attempt (a window,
            # or a rebuild's full compile) can't finish: stop here so the
            # MEASURED windows (including the stale pre-failure fallback)
            # reach the JSON line instead of the watchdog's stage snapshot
            errors.append("stopping early: wall-clock budget nearly "
                          f"exhausted ({_remaining():.0f}s left, "
                          f"need {margin:.0f}s)")
            _log(errors[-1])
            break
        try:
            if built is None:
                step, state, batch, batch_size, n_chips, devices = build_bench(
                    batch_per_chip, multistep
                )
                built = True
                t0 = time.perf_counter()
                warm_dispatches = max(1, math.ceil(WARMUP_STEPS / multistep))
                for _ in range(warm_dispatches):
                    state, loss = step(state, batch)
                # Timing is closed by a host fetch of the step's loss scalar:
                # on the experimental axon platform block_until_ready() on a
                # mesh-sharded state can return before execution completes,
                # but a device->host scalar transfer cannot.
                float(loss)
                _log(f"warmup {time.perf_counter() - t0:.1f}s "
                     f"(batch={batch_size}, multistep={multistep})")
                last_good = [step, state, batch, batch_size, n_chips, devices]
            w = len(window_dts)
            t0 = time.perf_counter()
            for _ in range(dispatches):
                state, loss = step(state, batch)
            float(loss)
            dt = time.perf_counter() - t0
            _log(f"window {w}: {dt / steps_per_window * 1e3:.1f} ms/step")
            window_dts.append(dt / steps_per_window)
            _WINDOWS_DONE = len(window_dts)
            if attempt and not recovered_noted:
                # a completed window on the rebuilt step = the outage is
                # over; journaled as a typed backend_recovered event
                sup.on_recovered(attempt)
                recovered_noted = True
            # the step donates its state input: refresh the snapshot so the
            # returned state is the LIVE buffer, not a donated husk
            last_good[1] = state
        except KeyboardInterrupt:
            raise
        except Exception as e:
            attempt += 1
            errors.append(f"{type(e).__name__}: {e}")
            _log(f"transient failure #{attempt} ({errors[-1][:200]})")
            # classification + budget + typed backend_lost event + the
            # shared retry event, all through the supervisor
            retrying = sup.on_failure(attempt, e, context="bench.window")
            recovered_noted = False
            if window_dts:
                stale_dts = window_dts
                window_dts = []  # discard pre-failure windows: one healthy
                                 # session only feeds the median
                _WINDOWS_DONE = 0  # keep the watchdog's count honest
            if not retrying:
                _log("not retrying: budget exhausted or unretryable "
                     "(version skew never heals mid-run)")
                break
            built = None  # rebuild: donated/invalid buffers are gone
            sup.recover(attempt)  # breadcrumb + backoff + cache clear
    if not window_dts and stale_dts:
        window_dts = stale_dts
        _WINDOWS_DONE = len(window_dts)
        errors.append("degraded: median from pre-failure windows")
    if last_good is None:
        return window_dts, None, None, None, 0, 0, [], errors
    step, state, batch, batch_size, n_chips, devices = last_good
    return (window_dts, step, state, batch, batch_size, n_chips, devices,
            errors)


def train_result_stub(args) -> dict:
    """The degraded-case contract line for the train bench: what the driver
    parses if nothing past argument parsing ever completes."""
    return {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
        "method": "wall_time",
        "batch_per_chip": args.batch,
        "multistep": args.multistep,
    }


#: RunJournal when --journal is set: the bench result then also lands as a
#: typed `bench` event (same schema tools/bench_models.py writes), so
#: BENCH_r0N trajectories are diffable with obs_report/check_journal and the
#: multistep/per-microstep fields are queryable instead of stdout-only
_JOURNAL = None


def _journal_result(result: dict) -> None:
    """Best-effort: the stdout contract line must never die to a journal
    I/O error."""
    if _JOURNAL is None:
        return
    try:
        _JOURNAL.bench(result.get("metric", "bench"), result)
        _JOURNAL.close()
    except Exception as e:
        _log(f"journal write failed ({type(e).__name__}: {e})")


def main(args, result: dict | None = None) -> None:
    if result is None:
        result = train_result_stub(args)
    try:
        # leave the watchdog 30s of headroom so a dead backend reports as
        # the specific liveness error, not the generic budget one
        probe_budget = min(INIT_BUDGET_S, max(1.0, _remaining() - 30.0))
        _log(f"backend liveness probe (budget {probe_budget:.0f}s)")
        t0 = time.perf_counter()
        ok, err = _backend_alive(probe_budget)
        if not ok:
            result["errors"] = [err]
            return  # degraded emission from finally
        _log(f"backend alive ({time.perf_counter() - t0:.1f}s)")
        try:
            result.update(_cold_start_fields())
            _log("cold-start probe: compile "
                 f"{result.get('warmup_compile_ms')}ms -> cache-warm "
                 f"{result.get('cold_start_ms')}ms")
        except Exception as e:  # the headline must survive a probe bug
            _log(f"cold-start probe failed ({type(e).__name__}: {e})")
        (window_dts, step, state, batch, batch_size, n_chips, devices,
         errors) = _timed_windows(args.batch, args.multistep)
        if errors:
            result["errors"] = errors[-3:]
        result["windows_completed"] = len(window_dts)
        if not window_dts:
            return  # degraded emission from finally
        _log(f"{n_chips}x {devices[0].device_kind} | resnet50 bf16 "
             f"batch={batch_size} image={IMAGE_SIZE}")

        wall_per_chip = batch_size / n_chips / float(np.median(window_dts))
        result["value"] = round(wall_per_chip, 1)
        result["vs_baseline"] = round(wall_per_chip / TARGET_PER_CHIP, 3)
        # per-MICROSTEP wall time + the dispatch arithmetic: without these a
        # multistep>1 round is incomparable to a multistep=1 one (the r0N
        # trajectory would silently mix steps-per-dispatch regimes)
        result["wall_ms_per_step"] = round(
            float(np.median(window_dts)) * 1e3, 3)
        result["dispatches_per_window"] = max(
            1, math.ceil(TIMED_STEPS / args.multistep))
        result["steps_per_dispatch"] = args.multistep

        # MFU / HBM traffic from XLA's post-fusion cost analysis (falls back
        # to analytic ResNet-50 flops). All per-chip: cost analysis is
        # per-device under SPMD and wall_per_chip is the per-chip rate.
        # NB "bytes accessed" is an UPPER BOUND on real HBM traffic: reads
        # served from VMEM-resident buffers still count, so the implied
        # bandwidth can exceed the 819 GB/s pin limit (batch 128 implies
        # ~946 GB/s — proof of the overcount; see
        # artifacts/batch_scaling_r04.json and the round-3 roofline
        # misread it caused).
        batch_per_chip = batch_size // n_chips
        flops_per_step, bytes_per_step, src = _cost_analysis(
            step, args.multistep, batch_per_chip
        )
        peak = _peak_flops(devices[0].device_kind)
        flops_per_image = flops_per_step / batch_per_chip
        result["model_flops_per_image"] = round(flops_per_image / 1e9, 2)
        result["flops_source"] = src
        result["mfu_wall_pct"] = round(
            100 * wall_per_chip * flops_per_image / peak, 1
        )
        if bytes_per_step is not None:
            result["hbm_gbytes_per_step_per_chip"] = round(
                bytes_per_step / 1e9, 2
            )
            result["hbm_gbytes_per_sec_per_chip"] = round(
                bytes_per_step / 1e9 * wall_per_chip / batch_per_chip, 1
            )

        # Device step time from a profiler trace. Wall differs from it only
        # by the per-host-sync relay latency amortized over the window
        # (~118 ms / TIMED_STEPS; mechanism measured in
        # artifacts/dispatch_r04.json — NOT a per-dispatch cost). Skipped
        # when the watchdog deadline is close: the wall headline above is
        # already measured and must not be lost to a trace-window hang.
        dev_ms = None
        if _remaining() > _TRACE_MARGIN_S:
            dev_ms = _device_step_ms(step, state, batch, args.multistep)
        else:
            _log("skipping device trace: budget nearly exhausted")
        if dev_ms is not None:
            dev_per_chip = batch_size / n_chips / (dev_ms / 1e3)
            _log(f"device step {dev_ms:.1f} ms")
            result["device_ms_per_step"] = round(dev_ms, 3)  # per microstep
            result["device_images_per_sec_per_chip"] = round(dev_per_chip, 1)
            result["device_vs_baseline"] = round(
                dev_per_chip / TARGET_PER_CHIP, 3
            )
            result["mfu_device_pct"] = round(
                100 * dev_per_chip * flops_per_image / peak, 1
            )
    except KeyboardInterrupt:
        raise
    except Exception as e:
        result["errors"] = result.get("errors", []) + [
            f"{type(e).__name__}: {e}"
        ]
        _log(f"fatal: {type(e).__name__}: {e}")
    finally:
        _emit(result)


def load_xspace(tmpdir: str):
    """Parse the xplane.pb a jax.profiler trace left under `tmpdir`.

    Shared by the module-event timing here and tools/roofline.py's
    DMA-byte walk. TF ships stale generated protos; the pure-python parser
    accepts them (must be set before google.protobuf first loads)."""
    import glob

    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    path = glob.glob(
        os.path.join(tmpdir, "**", "*.xplane.pb"), recursive=True
    )[0]
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    return xs


def _trace_module_events(step, state, batch, dispatches: int):
    """[(start_ps, duration_ps)] of device "XLA Modules" events from one
    traced window of `dispatches` executions, sorted by start time.

    The trace's "/device:TPU:0" plane holds one event per executed program
    whose duration is the device-side execution time of the whole jitted
    step (matmuls, DMAs and stalls included — everything but host/relay
    dispatch overhead). Shared with tools/dispatch_probe.py, which also
    needs the start timestamps for inter-module gap analysis. Raises on
    trace failure; callers decide the fallback.
    """
    import shutil
    import tempfile

    tmpdir = tempfile.mkdtemp(prefix="dv_bench_trace_")
    try:
        jax.profiler.start_trace(tmpdir)
        for _ in range(dispatches):
            state, loss = step(state, batch)
        float(loss)
        jax.profiler.stop_trace()
        xs = load_xspace(tmpdir)
        events = []
        for plane in xs.planes:
            if not plane.name.startswith("/device:TPU"):
                continue
            for line in plane.lines:
                if line.name != "XLA Modules":
                    continue
                for ev in line.events:
                    start_ps = line.timestamp_ns * 1000 + ev.offset_ps
                    events.append((start_ps, ev.duration_ps))
        events.sort()
        return events
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def _device_step_ms(step, state, batch, multistep: int = 1, n_steps: int = 10):
    """Median on-device ms/step from a jax.profiler trace (None on failure)."""
    dispatches = max(1, math.ceil(n_steps / multistep))
    try:
        events = _trace_module_events(step, state, batch, dispatches)
        durs = [d / 1e9 for _, d in events]  # ps -> ms
        if len(durs) < dispatches // 2:
            return None
        return float(np.median(durs)) / multistep
    except Exception as e:  # no TF proto, trace unsupported on backend, ...
        print(f"bench: no device trace ({type(e).__name__}: {e}); "
              "falling back to wall time", file=sys.stderr)
        return None


def sweep_main(out_path: str) -> None:
    """Dispatch-overhead / batch sweep: interleaved windows across configs.

    Session-to-session wall drift on this rig is +-4%; only interleaved
    same-process windows give trustworthy relative numbers. Builds every
    config up front, then round-robins the timed windows. The wall-minus-
    device gap this reports is the per-host-sync latency amortized over the
    window (see artifacts/dispatch_r04.json and tools/dispatch_probe.py; it
    is NOT per-dispatch — r3 misread it that way). For the batch scaling
    curve proper, use tools/batch_sweep.py.
    """
    configs = [(256, 1), (256, 8), (512, 1), (512, 8)]
    built = {}
    errors = []
    for bpc, ms in configs:
        try:
            step, state, batch, batch_size, n_chips, devices = build_bench(
                bpc, ms
            )
            t0 = time.perf_counter()
            warm_dispatches = max(1, math.ceil(WARMUP_STEPS / ms))
            for _ in range(warm_dispatches):
                state, loss = step(state, batch)
            float(loss)
            _log(f"sweep warmup b{bpc} k{ms}: "
                 f"{time.perf_counter() - t0:.1f}s")
            built[(bpc, ms)] = [step, state, batch, batch_size, n_chips, []]
        except KeyboardInterrupt:
            raise
        except Exception as e:  # config dropped, sweep continues
            errors.append(f"warmup b{bpc} k{ms}: {type(e).__name__}: {e}")
            _log(errors[-1][:200])
    for w in range(WINDOWS):
        for key in list(built):
            step, state, batch, batch_size, n_chips, dts = built[key]
            ms = key[1]
            dispatches = max(1, math.ceil(TIMED_STEPS / ms))
            try:
                t0 = time.perf_counter()
                for _ in range(dispatches):
                    state, loss = step(state, batch)
                float(loss)
                dts.append((time.perf_counter() - t0) / (dispatches * ms))
                built[key][1] = state
            except KeyboardInterrupt:
                raise
            except Exception as e:  # donated state is gone: drop the config
                errors.append(
                    f"window b{key[0]} k{ms}: {type(e).__name__}: {e}"
                )
                _log(errors[-1][:200])
                del built[key]
    rows = []
    for (bpc, ms), (step, state, batch, batch_size, n_chips, dts) in (
            built.items()):
        if not dts:
            continue
        wall_ms = float(np.median(dts)) * 1e3
        try:
            dev = _device_step_ms(step, state, batch, ms)
        except Exception:
            dev = None
        rows.append({
            "batch_per_chip": bpc,
            "steps_per_dispatch": ms,
            "wall_ms_per_step": round(wall_ms, 2),
            "device_ms_per_step": round(dev, 2) if dev else None,
            "dispatch_overhead_ms_per_step": (
                round(wall_ms - dev, 2) if dev else None
            ),
            "wall_images_per_sec_per_chip": round(
                batch_size / n_chips / wall_ms * 1e3, 1
            ),
        })
        _log(f"sweep b{bpc} k{ms}: wall {wall_ms:.1f} ms/step, "
             f"device {dev and round(dev, 1)} ms/step")
    artifact = {
        "what": "wall vs device per-step time across batch size and "
                "steps-per-dispatch (lax.scan superstep), interleaved "
                "windows, one process",
        "rows": rows,
    }
    try:
        artifact["device_kind"] = jax.devices()[0].device_kind
    except Exception:
        pass
    if errors:
        artifact["errors"] = errors[-5:]
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2)
    # the one-line JSON contract holds even for a fully-failed sweep
    _emit({"metric": "dispatch_sweep", "artifact": out_path,
           "rows": rows, **({"errors": errors[-3:]} if errors else {})})


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--data", choices=["host", "fused"], default=None,
                        help="benchmark the input pipeline instead of the "
                             "train step")
    parser.add_argument("--num-procs", type=int, default=0,
                        help="decode worker processes (0 = thread pool)")
    parser.add_argument("--batch", type=int, default=BATCH_PER_CHIP,
                        help="per-chip batch size")
    parser.add_argument("--multistep", type=int, default=1,
                        help="optimizer steps per dispatch (lax.scan "
                             "superstep)")
    parser.add_argument("--sweep", metavar="OUT_JSON", default=None,
                        help="run the dispatch-overhead/batch sweep and "
                             "write the artifact JSON")
    parser.add_argument("--multichip", action="store_true",
                        help="MULTICHIP scaling mode: time a table-sharded "
                             "train step at data={1,2,4,8} sub-meshes and "
                             "emit the scaling-efficiency block (throughput, "
                             "per-device examples/s, efficiency vs the "
                             "1-device baseline) — the perf number that "
                             "replaces the dryrun's loss=OK smoke "
                             "(BENCH_MULTICHIP_STEPS/_BATCH tune the "
                             "windows)")
    parser.add_argument("--flight-dir", default=None, metavar="DIR",
                        help="flight recorder (obs/flight.py): dump a "
                             "postmortem bundle under DIR if the bench "
                             "dies (recovery breadcrumbs included)")
    parser.add_argument("--journal", default=None, metavar="PATH",
                        help="also write the result as a typed `bench` "
                             "journal event (obs/journal.py schema; "
                             "validate with tools/check_journal.py)")
    args = parser.parse_args()
    if args.journal:
        from deep_vision_tpu.obs.journal import RunJournal

        _JOURNAL = RunJournal(args.journal, kind="bench")
        _JOURNAL.manifest(config={"tool": "bench", "batch": args.batch,
                                  "multistep": args.multistep,
                                  "data": args.data, "sweep": args.sweep})
    if args.flight_dir:
        from deep_vision_tpu.obs import FlightRecorder, set_flight

        set_flight(FlightRecorder(args.flight_dir))
    if args.data:
        stub = {
            "metric": f"imagenet_pipeline_{args.data}_images_per_sec_per_core",
            "value": 0.0, "unit": "images/sec/core", "vs_baseline": 0.0,
        }
        # 'host' mode never touches a device: no liveness gate needed
        run = lambda: data_main(args.data, args.num_procs)
        needs_device = args.data == "fused"
    elif args.multichip:
        stub = multichip_result_stub()
        run = lambda: multichip_main(stub)
        needs_device = True
    elif args.sweep:
        stub = {"metric": "dispatch_sweep", "artifact": args.sweep,
                "rows": []}
        run = lambda: sweep_main(args.sweep)
        needs_device = True
    else:
        stub = train_result_stub(args)
        run = lambda: main(args, stub)
        needs_device = False  # main() runs its own gate with headroom
    _start_watchdog(stub)
    try:
        if needs_device:
            ok, err = _backend_alive(INIT_BUDGET_S)
            if not ok:
                stub["errors"] = [err]
                _emit(stub)
                _hard_exit(0)
        run()
    except KeyboardInterrupt:
        raise
    except Exception as e:
        # the contract line must exist even for failures outside main()'s
        # own try/finally (e.g. a fixture-dir write error in data_main)
        stub["errors"] = stub.get("errors", []) + [f"{type(e).__name__}: {e}"]
        _log(f"fatal: {type(e).__name__}: {e}")
        try:
            from deep_vision_tpu.obs import flight as _flight

            _flight.emergency_dump("crash")
        except Exception:
            pass
        _emit(stub)
    # hard exit, not fall-through: after a degraded run a wedged jax client
    # thread can hang interpreter teardown past the driver's timeout, which
    # is exactly the rc:124 this file exists to prevent. The contract line
    # is already flushed.
    _hard_exit(0)
