"""Declarative sharding tables (parallel/shardmap.py) + wiring.

Table semantics (integer -> `*` normalization, first-match-wins
ordering, catch-all enforcement, unknown-axis / rank-mismatch refusal,
depth-independent resolution), the curated family tables against real
model states, the Trainer/train_cli wiring with its typed
`sharding_resolved` event, the coverage-failure messages that name leaf
paths, the ring-attention flash-floor routing, scaling-efficiency rows,
and the obs tooling (check_journal schema, obs_report section with its
byte-unchanged gate).
"""
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from deep_vision_tpu.core.train_state import create_train_state  # noqa: E402
from deep_vision_tpu.losses.classification import (  # noqa: E402
    classification_loss_fn,
)
from deep_vision_tpu.models.vit import ViT  # noqa: E402
from deep_vision_tpu.parallel.mesh import (  # noqa: E402
    ShardingCoverageError,
    assert_sharding_coverage,
    create_mesh,
    data_sharding,
    infer_tp_sharding,
    sharding_coverage,
    stacked_data_sharding,
)
from deep_vision_tpu.parallel.shardmap import (  # noqa: E402
    FAMILY_RULES,
    HeuristicRules,
    MOE_RULES,
    RESNET_RULES,
    VIT_RULES,
    ShardingRuleError,
    ShardingRules,
    get_rules,
    normalize_path,
    resolution_event_fields,
    rules_for,
)
from deep_vision_tpu.train.optimizers import build_optimizer  # noqa: E402

from tools.check_journal import check_journal  # noqa: E402


def tiny_vit(num_experts: int = 0, depth: int = 2) -> ViT:
    return ViT(depth=depth, dim=16, num_heads=2, patch=8, num_classes=8,
               num_experts=num_experts)


def tiny_state(model=None):
    tx = build_optimizer("sgd", learning_rate=0.05, momentum=0.9)
    return create_train_state(model or tiny_vit(), tx,
                              jnp.ones((2, 16, 16, 3), jnp.float32))


# -- normalization ------------------------------------------------------------

class TestNormalize:
    def test_integer_tokens_become_star(self):
        assert normalize_path("layers.11.attention.wo.weight") == \
            "layers.*.attention.wo.weight"

    def test_optimizer_state_indices_normalize(self):
        assert normalize_path("opt_state.1.0.trace.Dense_0.kernel") == \
            "opt_state.*.*.trace.Dense_0.kernel"

    def test_flax_layer_suffixes_stay_literal(self):
        # Mlp_0.Dense_0 vs Mlp_0.Dense_1 distinguishes the column- from
        # the row-parallel projection; the PATTERN's glob generalizes
        # over layer indices instead
        assert normalize_path("params.ViTBlock_7.Mlp_0.Dense_1.kernel") == \
            "params.ViTBlock_7.Mlp_0.Dense_1.kernel"


# -- table construction -------------------------------------------------------

class TestConstruction:
    def test_catch_all_required(self):
        with pytest.raises(ShardingRuleError, match="catch-all"):
            ShardingRules(name="t", rules=(("*.kernel", (None, "model")),))

    def test_catch_all_must_be_last(self):
        with pytest.raises(ShardingRuleError, match="catch-all"):
            ShardingRules(name="t", rules=(
                ("*", ()), ("*.kernel", (None, "model"))))

    def test_empty_table_refused(self):
        with pytest.raises(ShardingRuleError, match="no rules"):
            ShardingRules(name="t", rules=())

    def test_duplicate_pattern_refused(self):
        with pytest.raises(ShardingRuleError, match="duplicate"):
            ShardingRules(name="t", rules=(
                ("*.kernel", (None, "model")),
                ("*.kernel", ()),
                ("*", ())))

    def test_malformed_spec_refused(self):
        with pytest.raises(ShardingRuleError, match="spec"):
            ShardingRules(name="t", rules=(("*", "model"),))
        with pytest.raises(ShardingRuleError, match="entry"):
            ShardingRules(name="t", rules=(("*", (42,)),))

    def test_malformed_batch_axes_refused(self):
        # empty / non-string batch axes refuse at construction (the
        # same loud contract the rule specs have), a typo'd-but-
        # string axis at resolve — never a KeyError mid-train-step
        with pytest.raises(ShardingRuleError, match="batch_axes"):
            ShardingRules(name="t", rules=(("*", ()),), batch_axes=())
        with pytest.raises(ShardingRuleError, match="batch_axes"):
            ShardingRules(name="t", rules=(("*", ()),),
                          batch_axes=("data", 3))

    def test_unknown_batch_axis_refused_at_resolve(self, mesh4x2):
        table = ShardingRules(name="t", rules=(("*", ()),),
                              batch_axes=("dp",))
        with pytest.raises(ShardingRuleError, match="batch axis"):
            table.resolve({"a": jnp.ones((4,))}, mesh4x2)
        with pytest.raises(ShardingRuleError, match="batch axis"):
            HeuristicRules(batch_axes=("dp",)).resolve(
                {"a": jnp.ones((4,))}, mesh4x2)


# -- matching semantics -------------------------------------------------------

class TestMatching:
    def test_first_match_wins(self):
        table = ShardingRules(name="t", rules=(
            ("*.Mlp_*.Dense_0.kernel", (None, "model")),
            ("*.Dense_*.kernel", ("model", None)),
            ("*", ())))
        pat, spec = table.match("params.ViTBlock_0.Mlp_0.Dense_0.kernel")
        assert pat == "*.Mlp_*.Dense_0.kernel" and spec == (None, "model")
        pat, spec = table.match("params.Dense_0.kernel")
        assert pat == "*.Dense_*.kernel" and spec == ("model", None)

    def test_momentum_paths_match_param_rules(self, mesh4x2):
        # leading-* rules claim the optimizer moment mirrors too: the
        # momentum of a sharded kernel shards with it
        state = tiny_state()
        shardings, _ = VIT_RULES.resolve(state, mesh4x2)
        mom = jax.tree_util.tree_leaves(shardings.opt_state)
        assert any(
            any(e is not None for e in tuple(s.spec)) for s in mom
        ), "no optimizer-state leaf sharded"

    def test_integer_normalized_match(self, mesh4x2):
        # torch-style integer layer indices resolve through the same
        # table row (the snippet's layers.*.attention.wo.weight shape)
        table = ShardingRules(name="t", rules=(
            ("layers.*.wo.weight", (None, "model")), ("*", ())))
        tree = {"layers": {str(i): {"wo": {"weight": jnp.ones((4, 8))}}
                           for i in range(3)}}
        _, report = table.resolve(tree, mesh4x2)
        assert report["rules"]["layers.*.wo.weight"] == 3
        assert report["sharded_leaves"] == 3


# -- resolve refusals ---------------------------------------------------------

class TestRefusals:
    def test_unknown_axis_refused(self, mesh4x2):
        table = ShardingRules(name="t", rules=(
            ("*.kernel", (None, "tp")), ("*", ())))
        tree = {"a": {"kernel": jnp.ones((4, 8))}}
        with pytest.raises(ShardingRuleError, match="unknown mesh axis"):
            table.resolve(tree, mesh4x2)

    def test_rank_mismatch_refused(self, mesh4x2):
        table = ShardingRules(name="t", rules=(
            ("*.kernel", (None, None, "model")), ("*", ())))
        tree = {"a": {"kernel": jnp.ones((4, 8))}}
        with pytest.raises(ShardingRuleError, match="rank"):
            table.resolve(tree, mesh4x2)

    def test_non_divisible_dim_drops_axis(self, mesh4x2):
        # the replace_on_mesh convention: an odd-width layer replicates
        # that dim (counted in the report) instead of failing the family
        table = ShardingRules(name="t", rules=(
            ("*.kernel", (None, "model")), ("*", ())))
        tree = {"a": {"kernel": jnp.ones((4, 7))}}  # 7 % 2 != 0
        shardings, report = table.resolve(tree, mesh4x2)
        assert len(report["dropped_dims"]) == 1
        assert report["sharded_leaves"] == 0
        spec = tuple(shardings["a"]["kernel"].spec)
        assert all(e is None for e in spec)

    def test_size_one_axis_resolves_replicated(self, mesh8):
        # a model-axis spec on a pure-DP mesh must NOT count as sharded
        table = ShardingRules(name="t", rules=(
            ("*.kernel", (None, "model")), ("*", ())))
        tree = {"a": {"kernel": jnp.ones((4, 8))}}
        _, report = table.resolve(tree, mesh8)
        assert report["sharded_leaves"] == 0
        assert table.floor_for(mesh8) == 0  # floor waived without TP


# -- depth independence -------------------------------------------------------

class TestDepthIndependence:
    def test_same_table_resolves_all_depths(self, mesh4x2):
        """The acceptance shape: one table, depth-8 and depth-12 ViTs,
        identical per-normalized-path resolution."""
        def spec_map(depth):
            state = tiny_state(tiny_vit(depth=depth))
            shardings, _ = VIT_RULES.resolve(state, mesh4x2)
            flat, _ = jax.tree_util.tree_flatten_with_path(
                shardings, is_leaf=lambda x: hasattr(x, "spec"))
            from deep_vision_tpu.parallel.shardmap import leaf_path

            out = {}
            for p, s in flat:
                # collapse the layer index so depth-8 and depth-12 rows
                # land on the same key
                key = normalize_path(leaf_path(p))
                import re

                key = re.sub(r"ViTBlock_\d+", "ViTBlock_N", key)
                out.setdefault(key, set()).add(tuple(s.spec))
            return out

        m8, m12 = spec_map(8), spec_map(12)
        assert set(m8) == set(m12)
        for k in m8:
            assert m8[k] == m12[k], f"resolution drifted at {k}"
            assert len(m8[k]) == 1, f"inconsistent specs within depth at {k}"


# -- curated tables over real states ------------------------------------------

class TestFamilyTables:
    def test_vit_beats_heuristic(self, mesh4x2):
        state = tiny_state()
        shardings, report = VIT_RULES.resolve(state, mesh4x2)
        heur = sharding_coverage(
            state, infer_tp_sharding(state, mesh4x2, min_size=1024))
        assert report["sharded_leaves"] >= VIT_RULES.min_sharded
        assert report["sharded_leaves"] > heur["sharded"]
        assert report["unmatched"] == 0
        assert_sharding_coverage(state, shardings, mesh4x2,
                                 min_sharded=VIT_RULES.floor_for(mesh4x2))

    def test_moe_expert_router_split(self, mesh4x2):
        state = tiny_state(tiny_vit(num_experts=4))
        shardings, report = MOE_RULES.resolve(state, mesh4x2)
        moe = shardings.params["ViTBlock_1"]["MoeMlp_0"]
        assert tuple(moe["w1"].spec)[0] == "model"
        assert tuple(moe["w2"].spec)[0] == "model"
        assert all(e is None for e in tuple(moe["router"].spec))
        assert report["rules"]["*.MoeMlp_*.w1"] > 0

    def test_resnet_table_covers_dryrun_model(self, mesh4x2):
        from deep_vision_tpu.models.resnet import BottleneckBlock, ResNet

        model = ResNet(stage_sizes=(1, 1, 1, 1), block=BottleneckBlock,
                       width=16, num_classes=64)
        tx = build_optimizer("sgd", learning_rate=0.1, momentum=0.9,
                             weight_decay=1e-4)
        state = create_train_state(model, tx,
                                   jnp.ones((2, 32, 32, 3), jnp.float32))
        _, report = RESNET_RULES.resolve(state, mesh4x2)
        heur = sharding_coverage(
            state, infer_tp_sharding(state, mesh4x2, min_size=1024))
        assert report["sharded_leaves"] >= RESNET_RULES.min_sharded
        assert report["sharded_leaves"] >= heur["sharded"]
        assert report["unmatched"] == 0

    def test_registry_lookup(self):
        assert rules_for("vit_s16") is VIT_RULES
        assert rules_for("vit_b16") is VIT_RULES
        assert rules_for("vmoe_s16") is MOE_RULES
        assert rules_for("resnet50") is RESNET_RULES
        assert rules_for("yolov3") is None

    def test_get_rules_cli_semantics(self):
        assert get_rules("vit") is VIT_RULES
        assert get_rules("auto", "resnet50") is RESNET_RULES
        assert isinstance(get_rules("heuristic"), HeuristicRules)
        with pytest.raises(ShardingRuleError, match="no curated table"):
            get_rules("auto", "yolov3")
        with pytest.raises(ShardingRuleError, match="unknown"):
            get_rules("vitt")

    def test_heuristic_rules_match_infer_tp(self, mesh4x2):
        state = tiny_state()
        h = HeuristicRules(min_size=1024)
        shardings, report = h.resolve(state, mesh4x2)
        direct = sharding_coverage(
            state, infer_tp_sharding(state, mesh4x2, min_size=1024))
        assert report["sharded_leaves"] == direct["sharded"]
        assert report["model"] == "heuristic"

    def test_all_tables_have_floor_and_catch_all(self):
        for name, table in FAMILY_RULES.items():
            assert table.rules[-1][0] == "*", name
            assert table.min_sharded > 0, name
            assert table.batch_axes == ("data",), name


# -- coverage failure messages ------------------------------------------------

class TestCoverageMessages:
    def test_floor_failure_names_replicated_paths(self, mesh4x2):
        """Satellite: the 108 -> 34 regression was undebuggable from
        bare counts — the floor failure must NAME the leaves that fell
        back to replication."""
        state = tiny_state()
        gutted = ShardingRules(name="vit", rules=(("*", ()),),
                               min_sharded=12)
        shardings, _ = gutted.resolve(state, mesh4x2)
        with pytest.raises(ShardingCoverageError) as ei:
            assert_sharding_coverage(state, shardings, mesh4x2,
                                     min_sharded=12)
        msg = str(ei.value)
        assert "replicated float leaves" in msg
        assert "ViTBlock" in msg  # real leaf paths, not counts

    def test_unmatched_failure_still_names_paths(self, mesh4x2):
        state = tiny_state()
        shardings, _ = VIT_RULES.resolve(state.params, mesh4x2)
        # shardings for params only, checked against the full state:
        # every non-params float leaf is unmatched
        with pytest.raises(ShardingCoverageError, match="NO sharding"):
            assert_sharding_coverage(state, shardings, mesh4x2)


# -- batch-axes placement helpers ---------------------------------------------

class TestBatchAxes:
    def test_data_sharding_axes(self, mesh4x2):
        s = data_sharding(mesh4x2, 4, axes=("data",))
        assert tuple(s.spec) == ("data", None, None, None)
        s2 = data_sharding(mesh4x2, 2, axes=("data", "model"))
        assert tuple(s2.spec)[0] == ("data", "model")

    def test_stacked_sharding_axes(self, mesh4x2):
        s = stacked_data_sharding(mesh4x2, 3, axes=("data",))
        assert tuple(s.spec) == (None, "data", None)


# -- Trainer wiring -----------------------------------------------------------

class TestTrainerWiring:
    @pytest.fixture(scope="class")
    def trained(self, tmp_path_factory):
        from deep_vision_tpu.obs.journal import RunJournal
        from deep_vision_tpu.train.trainer import Trainer

        path = str(tmp_path_factory.mktemp("shard") / "journal.jsonl")
        journal = RunJournal(path, kind="test")
        journal.manifest(config={"tool": "test_shardmap"})
        mesh = create_mesh(data=4, model=2)
        trainer = Trainer(
            tiny_vit(), build_optimizer("sgd", learning_rate=0.05,
                                        momentum=0.9),
            classification_loss_fn,
            jnp.ones((2, 16, 16, 3), jnp.float32), mesh=mesh,
            journal=journal, sharding_rules=VIT_RULES,
        )
        rng = np.random.RandomState(0)
        batch = {"image": rng.rand(8, 16, 16, 3).astype(np.float32),
                 "label": rng.randint(0, 8, (8,)).astype(np.int32)}
        metrics = trainer.train_step(batch)
        journal.close()
        return trainer, metrics, path

    def test_state_placed_per_table(self, trained):
        trainer, _, _ = trained
        qkv = trainer.state.params["ViTBlock_0"]["Attention_0"]["qkv"][
            "kernel"]
        assert "model" in tuple(qkv.sharding.spec)
        assert qkv.addressable_shards[0].data.size * 2 == qkv.size

    def test_step_runs_and_is_finite(self, trained):
        _, metrics, _ = trained
        assert np.isfinite(float(metrics["loss"]))

    def test_sharding_resolved_event_journaled_and_strict_valid(
            self, trained):
        _, _, path = trained
        with open(path) as fh:
            events = [json.loads(line) for line in fh if line.strip()]
        resolved = [e for e in events
                    if e["event"] == "sharding_resolved"]
        assert len(resolved) == 1
        e = resolved[0]
        assert e["model"] == "vit"
        assert e["sharded_leaves"] >= VIT_RULES.min_sharded
        assert e["mesh"] == {"data": 4, "model": 2}
        assert check_journal(path, strict=True) == []

    def test_gutted_table_fails_at_trainer_startup(self):
        from deep_vision_tpu.train.trainer import Trainer

        mesh = create_mesh(data=4, model=2)
        gutted = ShardingRules(name="vit", rules=(("*", ()),),
                               min_sharded=12)
        with pytest.raises(ShardingCoverageError, match="ViTBlock"):
            Trainer(tiny_vit(),
                    build_optimizer("sgd", learning_rate=0.05,
                                    momentum=0.9),
                    classification_loss_fn,
                    jnp.ones((2, 16, 16, 3), jnp.float32), mesh=mesh,
                    sharding_rules=gutted)

    def test_cli_flag_parses_to_rules(self):
        from deep_vision_tpu.train_cli import build_trainer  # noqa: F401
        # CLI surface: the flag exists and maps through get_rules
        import deep_vision_tpu.train_cli as cli

        src = open(cli.__file__).read()
        assert "--sharding-rules" in src


# -- sharding_resolved schema (check_journal) ---------------------------------

class TestSchema:
    def _line(self, tmp_path, **overrides):
        row = {"event": "sharding_resolved", "ts": 1.0, "run_id": "r",
               "model": "vit", "matched": 10, "unmatched": 0,
               "sharded_leaves": 8, "replicated": 2,
               "mesh": {"data": 4, "model": 2}}
        row.update(overrides)
        rows = [
            {"event": "run_manifest", "ts": 0.0, "run_id": "r",
             "kind": "test", "argv": []},
            row,
            {"event": "exit", "ts": 2.0, "run_id": "r", "status": "ok"},
        ]
        p = tmp_path / "j.jsonl"
        p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        return str(p)

    def test_valid_accepted(self, tmp_path):
        assert check_journal(self._line(tmp_path), strict=True) == []

    def test_bad_model_rejected(self, tmp_path):
        errs = check_journal(self._line(tmp_path, model=7), strict=True)
        assert any("model" in e for e in errs)

    def test_bad_counts_rejected(self, tmp_path):
        errs = check_journal(self._line(tmp_path, matched="10"),
                             strict=True)
        assert any("matched" in e for e in errs)

    def test_bad_mesh_rejected(self, tmp_path):
        errs = check_journal(self._line(tmp_path, mesh={}), strict=True)
        assert any("mesh" in e for e in errs)
        errs = check_journal(self._line(tmp_path, mesh={"data": "4"}),
                             strict=True)
        assert any("mesh" in e for e in errs)

    def test_missing_field_rejected(self, tmp_path):
        path = self._line(tmp_path)
        rows = [json.loads(line) for line in open(path)]
        del rows[1]["sharded_leaves"]
        with open(path, "w") as fh:
            fh.write("\n".join(json.dumps(r) for r in rows) + "\n")
        errs = check_journal(path, strict=True)
        assert any("sharded_leaves" in e for e in errs)

    def test_event_fields_helper_is_strict_valid(self, tmp_path, mesh4x2):
        state = tiny_state()
        _, report = VIT_RULES.resolve(state, mesh4x2)
        fields = resolution_event_fields(report)
        p = self._line(tmp_path, **fields)
        assert check_journal(p, strict=True) == []


# -- obs_report ----------------------------------------------------------------

class TestObsReport:
    def _events(self, with_sharding: bool):
        rows = [
            {"event": "run_manifest", "ts": 0.0, "run_id": "r",
             "kind": "test", "argv": []},
            {"event": "step", "ts": 1.0, "run_id": "r", "step": 1,
             "step_time_ms": 10.0},
            {"event": "exit", "ts": 2.0, "run_id": "r", "status": "ok"},
        ]
        if with_sharding:
            rows.insert(1, {
                "event": "sharding_resolved", "ts": 0.5, "run_id": "r",
                "model": "vit", "matched": 10, "unmatched": 1,
                "sharded_leaves": 8, "replicated": 3, "float_leaves": 11,
                "mesh": {"data": 4, "model": 2},
                "rules": {"*.qkv.kernel": 4, "*": 1},
                "unmatched_paths": ["params.odd.leaf"]})
            rows.insert(2, {
                "event": "bench", "ts": 0.7, "run_id": "r",
                "name": "multichip_scaling",
                "result": {"metric": "multichip_scaling", "rows": [
                    {"data": 1, "examples_per_sec": 100.0,
                     "per_device_examples_per_sec": 100.0,
                     "efficiency": 1.0},
                    {"data": 8, "examples_per_sec": 640.0,
                     "per_device_examples_per_sec": 80.0,
                     "efficiency": 0.8}]}})
        return rows

    def test_sharding_section_renders(self):
        from tools.obs_report import render, summarize_run

        text = render(summarize_run(self._events(True)))
        assert "sharding vit" in text
        assert "8 sharded / 3 replicated" in text
        assert "*.qkv.kernel -> 4 leaves" in text
        assert "scaling data=8" in text and "efficiency 0.8" in text

    def test_report_byte_unchanged_without_sharding_events(self):
        from tools.obs_report import render, summarize_run

        base = self._events(False)
        text = render(summarize_run(list(base)))
        assert "sharding" not in text and "scaling" not in text
        # and sweep-style bench rows (no efficiency key) don't trigger it
        base.insert(1, {"event": "bench", "ts": 0.5, "run_id": "r",
                        "name": "dispatch_sweep",
                        "result": {"rows": [{"batch_per_chip": 256}]}})
        assert "scaling data" not in render(summarize_run(base))


# -- scaling rows --------------------------------------------------------------

@pytest.mark.slow
class TestScaling:
    def test_measure_scaling_rows(self):
        from deep_vision_tpu.tools.scaling import (
            measure_scaling,
            scaling_result,
        )

        rows = measure_scaling(sub_sizes=(1, 2), batch_per_device=2,
                               steps=2, warmup=1)
        assert [r["data"] for r in rows] == [1, 2]
        assert rows[0]["efficiency"] == 1.0
        assert all(r["examples_per_sec"] > 0 for r in rows)
        result = scaling_result(rows)
        assert result["metric"] == "multichip_scaling"
        assert result["value"] == rows[-1]["efficiency"]

    def test_oversized_meshes_skipped(self):
        from deep_vision_tpu.tools.scaling import measure_scaling

        rows = measure_scaling(sub_sizes=(1, 16), batch_per_device=2,
                               steps=1, warmup=1)
        assert [r["data"] for r in rows] == [1]


# -- ring-attention flash floor (satellite) -----------------------------------

class TestRingFlashFloor:
    def test_routes_through_flash_min_tokens(self, monkeypatch):
        from deep_vision_tpu.parallel.ring_attention import (
            _default_use_flash,
        )

        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        monkeypatch.delenv("DVT_FLASH_MIN_TOKENS", raising=False)
        assert _default_use_flash(1024) is True
        assert _default_use_flash(512) is False
        # the PR 14 knob governs the ring path like it governs ViT
        monkeypatch.setenv("DVT_FLASH_MIN_TOKENS", "4096")
        assert _default_use_flash(2048) is False
        assert _default_use_flash(4096) is True
        monkeypatch.setenv("DVT_FLASH_MIN_TOKENS", "lots")
        with pytest.raises(ValueError, match="DVT_FLASH_MIN_TOKENS"):
            _default_use_flash(512)

    def test_block_divisibility_guard(self, monkeypatch):
        # a lowered floor must not route a shard the kernel's
        # t % block grid assert would reject — dense body instead
        # (the t % 1024 == 0 guard models/vit.py keeps)
        from deep_vision_tpu.parallel.ring_attention import (
            _default_use_flash,
        )

        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        monkeypatch.setenv("DVT_FLASH_MIN_TOKENS", "512")
        assert _default_use_flash(768) is False
        assert _default_use_flash(2048) is True

    def test_cpu_never_routes_to_flash(self, monkeypatch):
        from deep_vision_tpu.parallel.ring_attention import (
            _default_use_flash,
        )

        monkeypatch.setenv("DVT_FLASH_MIN_TOKENS", "1")
        assert _default_use_flash(4096) is False  # cpu backend

    def test_floor_shared_with_vit(self):
        import importlib

        vit_mod = importlib.import_module("deep_vision_tpu.models.vit")
        # ops.pallas re-exports the flash_attention FUNCTION, shadowing
        # the module attribute — import the module by dotted name
        fa = importlib.import_module(
            "deep_vision_tpu.ops.pallas.flash_attention")

        assert vit_mod.flash_min_tokens is fa.flash_min_tokens
        assert vit_mod.FLASH_MIN_TOKENS == fa.FLASH_MIN_TOKENS
