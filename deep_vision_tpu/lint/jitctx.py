"""Jit-context resolver: which functions in a module end up traced.

The rules (DV001 host-sync, DV005 impurity, DV006 python-branch) only
apply *inside* code that XLA traces — and in this codebase the jit
boundary is rarely a decorator. The Trainer jits bound methods
(`jax.jit(self._train_step_impl, donate_argnums=0)`), inference jits
partials (`jax.jit(functools.partial(yolo_detect, ...))`), the parallel
layer hands bodies to `jax.shard_map`, and checkify wraps the step
before the jit sees it. This module resolves all of those shapes to the
`ast.FunctionDef`s whose bodies are traced, plus the list of jit
*binding sites* (with their donation kwargs) that DV003 audits.

Resolution is intra-module by design: a name passed to `jax.jit` is
looked up among the module's function defs (at any nesting depth) after
chasing simple aliases (`x = f`, `x = functools.partial(f, ...)`,
`x = checkify.checkify(f)`). Cross-module calls from inside a traced
body are not followed — the rules stay local and predictable, and the
suppression syntax covers the rare miss.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Union

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]

# a call/decorator whose last dotted component is one of these IS the
# jit boundary (jax.jit, pjit, flax.linen.jit, bare `jit` import)
JIT_NAMES = {"jit", "pjit"}

# transforms that trace their callable argument without being a jit
# binding site of their own (no donation contract to audit)
TRACER_CONSUMERS = {
    "grad", "value_and_grad", "vmap", "pmap", "checkpoint", "remat",
    "shard_map", "scan", "while_loop", "cond", "fori_loop", "map",
    "switch", "associative_scan", "custom_vjp", "custom_jvp", "checkify",
}

# consumer names that collide with Python builtins/common identifiers: as a
# BARE name (`map(fn, xs)`) they are almost certainly not JAX — require the
# dotted form (`jax.lax.map`, `lax.scan`, `jax.checkpoint`) to count
AMBIGUOUS_BARE = {"map", "checkpoint", "cond", "scan", "switch"}

# wrappers that forward their first argument's body into the trace
PASSTHROUGH = {"partial", "checkify", "named_call", "wraps"}


def is_consumer_expr(node: ast.AST) -> bool:
    name = last_name(node)
    if name not in TRACER_CONSUMERS:
        return False
    if isinstance(node, ast.Name) and name in AMBIGUOUS_BARE:
        return False
    return True


def jax_random_aliases(tree: ast.Module) -> set:
    """Local names bound to the jax.random module (`from jax import random`,
    `import jax.random as jr`), so rules can recognize `random.normal(...)`
    as a JAX sampler rather than stdlib impurity."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for a in node.names:
                if a.name == "random":
                    out.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.random" and a.asname:
                    out.add(a.asname)
    return out


def last_name(node: ast.AST) -> Optional[str]:
    """foo -> 'foo'; a.b.jit -> 'jit'; anything else -> None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def root_name(node: ast.AST) -> Optional[str]:
    """np.random.normal -> 'np'; foo -> 'foo'."""
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def is_jit_expr(node: ast.AST) -> bool:
    return last_name(node) in JIT_NAMES


def has_donation(call: ast.Call) -> bool:
    return any(
        kw.arg in ("donate_argnums", "donate_argnames") for kw in call.keywords
    )


@dataclasses.dataclass
class JitSite:
    """One place a function is bound to jax.jit/pjit."""

    node: ast.AST  # the Call or decorator expression (has lineno/col)
    target: Optional[FunctionNode]  # resolved def, if intra-module
    target_name: str  # best-effort name of what was jitted
    donated: bool  # donate_argnums/donate_argnames present


class JitContext:
    """Per-module map of traced functions and jit binding sites."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        self.sites: List[JitSite] = []
        self.traced: Set[FunctionNode] = set()
        self._defs: Dict[str, List[FunctionNode]] = {}
        self._aliases: Dict[str, ast.AST] = {}
        self._collect_defs()
        self._collect_aliases()
        self._scan()

    # -- indexing ----------------------------------------------------------
    def _collect_defs(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._defs.setdefault(node.name, []).append(node)

    def _collect_aliases(self) -> None:
        # simple single-target assigns: x = f / x = partial(f, ...) /
        # x = checkify.checkify(f). Last write wins; good enough for the
        # straight-line jit wiring these modules use.
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    self._aliases[t.id] = node.value

    def _unwrap(self, node: ast.AST, depth: int = 0):
        """Chase an expression to ('name', str) | ('lambda', node) | None."""
        if depth > 6 or node is None:
            return None
        if isinstance(node, ast.Lambda):
            return ("lambda", node)
        if isinstance(node, ast.Name):
            aliased = self._aliases.get(node.id)
            if aliased is not None and not isinstance(aliased, ast.Name):
                resolved = self._unwrap(aliased, depth + 1)
                if resolved is not None:
                    return resolved
            elif isinstance(aliased, ast.Name) and aliased.id != node.id:
                return self._unwrap(aliased, depth + 1)
            return ("name", node.id)
        if isinstance(node, ast.Attribute):
            # self._train_step_impl / module.fn: match by trailing name
            return ("name", node.attr)
        if isinstance(node, ast.Call) and last_name(node.func) in PASSTHROUGH:
            if node.args:
                return self._unwrap(node.args[0], depth + 1)
        return None

    def _resolve(self, node: ast.AST):
        """-> (target FunctionNode or None, display name)."""
        resolved = self._unwrap(node)
        if resolved is None:
            return None, last_name(node) or "<expr>"
        kind, val = resolved
        if kind == "lambda":
            return val, "<lambda>"
        defs = self._defs.get(val, [])
        return (defs[-1] if defs else None), val

    # -- site + consumer scan ----------------------------------------------
    def _mark(self, target: Optional[FunctionNode]) -> None:
        if target is not None:
            self.traced.add(target)

    def _scan(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                fname = last_name(node.func)
                if fname in JIT_NAMES and node.args:
                    target, name = self._resolve(node.args[0])
                    self._mark(target)
                    self.sites.append(
                        JitSite(node, target, name, has_donation(node))
                    )
                elif node.args and is_consumer_expr(node.func):
                    target, _ = self._resolve(node.args[0])
                    if target is None and fname in ("scan", "while_loop",
                                                    "cond", "fori_loop",
                                                    "switch", "map"):
                        # lax control flow takes the callable at varying
                        # positions; try every argument
                        for arg in node.args:
                            t, _ = self._resolve(arg)
                            self._mark(t)
                    else:
                        self._mark(target)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_decorators(node)

    def _scan_decorators(self, fn) -> None:
        for dec in fn.decorator_list:
            if is_jit_expr(dec):
                self.traced.add(fn)
                self.sites.append(JitSite(dec, fn, fn.name, False))
            elif isinstance(dec, ast.Call):
                if is_jit_expr(dec.func):
                    self.traced.add(fn)
                    self.sites.append(
                        JitSite(dec, fn, fn.name, has_donation(dec))
                    )
                elif last_name(dec.func) == "partial" and dec.args and \
                        is_jit_expr(dec.args[0]):
                    # @functools.partial(jax.jit, static_argnums=...)
                    self.traced.add(fn)
                    self.sites.append(
                        JitSite(dec, fn, fn.name, has_donation(dec))
                    )
                elif is_consumer_expr(dec.func):
                    self.traced.add(fn)
            elif is_consumer_expr(dec):
                self.traced.add(fn)

    # -- queries ------------------------------------------------------------
    def traced_functions(self) -> List[FunctionNode]:
        """Traced bodies, outermost first; nested defs inside a traced
        function are covered by walking the parent subtree, so they are
        not listed twice."""
        covered: Set[int] = set()
        out: List[FunctionNode] = []
        for fn in sorted(self.traced, key=lambda n: (n.lineno,
                                                     n.col_offset)):
            if id(fn) in covered:
                continue
            out.append(fn)
            for sub in ast.walk(fn):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                    covered.add(id(sub))
        return out
