"""Checkpoint/resume for the whole zoo.

Semantics preserved from the reference (SURVEY.md §2.6):
  (a) full training-state capture incl. optimizer + scheduler + metric history
      (torch dict at ResNet/pytorch/train.py:417-428);
  (b) resume-by-flag (`-c <ckpt>`, ResNet/pytorch/train.py:293-307);
  (c) best-val-only saving (YOLO/tensorflow/train.py:243-247);
  (d) keep-every vs max_to_keep policies (CycleGAN/tensorflow/train.py:142-143,
      DCGAN/tensorflow/main.py:40).

TPU-native mechanism: orbax async checkpointing of the TrainState pytree,
step-indexed directories, plus a small JSON sidecar for host-side state
(metric history, plateau-scheduler state) that must never enter jit.
"""
from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


def state_arrays(state) -> dict:
    """The serializable slice of a TrainState: arrays only, no apply_fn/tx
    closures. THE single definition — CheckpointManager.save/restore and the
    GAN trainers all build their trees from it."""
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
        "rng": state.rng,
    }


class CheckpointManager:
    def __init__(
        self,
        directory: str,
        max_to_keep: Optional[int] = 3,
        save_interval_steps: int = 1,
        best_mode: Optional[str] = None,  # None | 'min' | 'max'
        best_metric: Optional[str] = None,
    ):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._best_mode = best_mode
        self._best_metric = best_metric
        self._best_value = None
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=True,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    # -- host-side sidecar -------------------------------------------------
    def _sidecar_path(self, step: int) -> str:
        return os.path.join(self.directory, f"host_state_{step}.json")

    def save(self, step: int, state, host_state: Optional[dict] = None, metrics=None):
        """Save TrainState (async) + JSON host state. Returns True if saved."""
        if self._best_mode and metrics is not None and self._best_metric in metrics:
            v = float(metrics[self._best_metric])
            better = (
                self._best_value is None
                or (self._best_mode == "min" and v < self._best_value)
                or (self._best_mode == "max" and v > self._best_value)
            )
            if not better:
                return False
            self._best_value = v
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state_arrays(state))
        )
        # multi-host: orbax coordinates the array save across processes;
        # the JSON sidecar is host-side state, written once by the primary.
        # REQUIRES a shared checkpoint filesystem (the standard orbax
        # multi-host setup): non-primary hosts read the same sidecar on
        # restore. With per-host local directories they would see
        # host_state=None and resume with divergent plateau/LR state.
        if saved and host_state is not None and jax.process_index() == 0:
            with open(self._sidecar_path(step), "w") as f:
                json.dump(host_state, f)
        return saved

    def restore(self, state, step: Optional[int] = None):
        """Restore into the structure of `state`; returns (state, host_state)."""
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            return state, None
        template = state_arrays(state)
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(template)
        )
        state = state.replace(**restored)
        host_state = None
        sidecar = self._sidecar_path(step)
        if os.path.exists(sidecar):
            with open(sidecar) as f:
                host_state = json.load(f)
        return state, host_state

    def save_tree(self, step: int, tree, host_state: Optional[dict] = None):
        """Save an arbitrary array pytree (multi-model trainers: the GAN
        trainers save {'g': ..., 'd': ...} of per-state array dicts — the
        tf.train.Checkpoint(generator.., discriminator..) analog at
        CycleGAN/tensorflow/train.py:133-148)."""
        saved = self._mgr.save(step, args=ocp.args.StandardSave(tree))
        if saved and host_state is not None and jax.process_index() == 0:
            with open(self._sidecar_path(step), "w") as f:
                json.dump(host_state, f)
        return saved

    def restore_tree(self, template, step: Optional[int] = None):
        """Restore a pytree saved by `save_tree` into `template`'s structure;
        returns (tree, host_state) or (None, None) when nothing is saved."""
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            return None, None
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(template)
        )
        host_state = None
        sidecar = self._sidecar_path(step)
        if os.path.exists(sidecar):
            with open(sidecar) as f:
                host_state = json.load(f)
        return restored, host_state

    def restore_variables(self, step: Optional[int] = None) -> dict:
        """Template-free restore of just the model variables.

        Inference/export flows (tools/infer.py, tools/export.py) must not
        need to reconstruct the exact optimizer + schedule state tree the
        trainer saved — orbax can restore with the on-disk structure, and
        only `params`/`batch_stats` are kept. Returns a flax variables dict.
        """
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory!r}")
        restored = self._mgr.restore(step)
        out = {"params": restored["params"]}
        if restored.get("batch_stats"):
            out["batch_stats"] = restored["batch_stats"]
        return out

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()
