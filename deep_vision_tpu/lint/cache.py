"""Incremental lint cache: skip re-analyzing files that cannot have
changed their verdict.

A cached entry is keyed by (file content sha, rule-pack fingerprint).
The fingerprint folds in everything that can change a verdict WITHOUT
the linted file changing:

  - every .py source in the lint package itself (a rule edit must
    invalidate the whole cache),
  - the cross-file inputs distlint parses behind lru_cache — the
    check_journal schema registry (DV204), the knob registry (DV203),
    the mesh-axis constants (DV205),
  - the enabled-rule set (a --select/--disable run must not poison
    the full-run cache),
  - CACHE_VERSION, for format changes.

Entries store both kept and suppressed findings (the CLI summary
counts suppressions), one JSON file per linted path under
`artifacts/lint_cache/`. Everything is fail-open: an unreadable,
stale, or corrupt entry is a cache miss, and a write failure is
ignored — the cache can only ever make lint faster, never wrong or
broken. Disable per-run with `--no-cache`.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Iterable, List, Optional, Tuple

from deep_vision_tpu.lint.findings import Finding

CACHE_VERSION = 1

#: default location, relative to the lint root (repo root in practice)
DEFAULT_CACHE_DIR = os.path.join("artifacts", "lint_cache")

#: repo-relative files (beyond the lint package) whose content feeds
#: rule verdicts: the registries distlint parses behind lru_cache
_CROSS_FILE_DEPS = (
    "tools/check_journal.py",
    "deep_vision_tpu/core/knobs.py",
    "deep_vision_tpu/parallel/mesh.py",
)


def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _file_sha(path: str) -> str:
    try:
        with open(path, "rb") as f:
            return _sha(f.read())
    except OSError:
        return "missing"


def pack_fingerprint(enabled: Iterable[str],
                     root: Optional[str] = None) -> str:
    """One hash covering rule code + cross-file registries + the
    enabled-rule set; any change invalidates every cached entry."""
    root = os.path.abspath(root or os.getcwd())
    parts: List[str] = [f"v{CACHE_VERSION}",
                        "rules=" + ",".join(sorted(enabled))]
    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    for fn in sorted(os.listdir(pkg_dir)):
        if fn.endswith(".py"):
            parts.append(f"{fn}={_file_sha(os.path.join(pkg_dir, fn))}")
    for rel in _CROSS_FILE_DEPS:
        parts.append(f"{rel}={_file_sha(os.path.join(root, rel))}")
    return _sha("\n".join(parts).encode())


class LintCache:
    """Per-file verdict store; every method is fail-open."""

    def __init__(self, cache_dir: str, fingerprint: str):
        self.cache_dir = cache_dir
        self.fingerprint = fingerprint
        self.hits = 0
        self.misses = 0

    def _entry_path(self, relpath: str) -> str:
        return os.path.join(self.cache_dir,
                            _sha(relpath.encode())[:24] + ".json")

    def get(self, relpath: str,
            source: str) -> Optional[Tuple[List[Finding], List[Finding]]]:
        try:
            with open(self._entry_path(relpath)) as f:
                doc = json.load(f)
            if (doc.get("version") != CACHE_VERSION
                    or doc.get("fingerprint") != self.fingerprint
                    or doc.get("path") != relpath
                    or doc.get("sha") != _sha(source.encode())):
                self.misses += 1
                return None
            kept = [Finding(**row) for row in doc["kept"]]
            dropped = [Finding(**row) for row in doc["suppressed"]]
        except (OSError, ValueError, TypeError, KeyError):
            self.misses += 1
            return None
        self.hits += 1
        return kept, dropped

    def put(self, relpath: str, source: str,
            kept: List[Finding], dropped: List[Finding]) -> None:
        doc = {
            "version": CACHE_VERSION,
            "fingerprint": self.fingerprint,
            "path": relpath,
            "sha": _sha(source.encode()),
            "kept": [dataclasses.asdict(f) for f in kept],
            "suppressed": [dataclasses.asdict(f) for f in dropped],
        }
        path = self._entry_path(relpath)
        tmp = path + f".tmp.{os.getpid()}"
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
