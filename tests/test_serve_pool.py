"""Fleet-layer tier-1 suite: ReplicaPool routing + death/respawn,
admission control + shed determinism, the canary weight-swap state
machine (promote AND rollback, zero recompiles via the compile
counter), the four new journal schemas, the obs_report fleet section,
and a locksmith-armed pool lifecycle with zero violations.

Runs on the pure-jnp toy model like tests/test_serve.py; the
sustained-RPS fleet scenario is `make fleet-smoke` (tools/loadgen.py).
"""
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.obs import RunJournal, locksmith, read_journal
from deep_vision_tpu.obs.registry import Registry
from deep_vision_tpu.obs.stepclock import recompile_count
from deep_vision_tpu.resilience import FaultInjected, faults
from deep_vision_tpu.serve import (
    SHED_REASONS,
    SWAP_OUTCOMES,
    SWAP_PHASES,
    AdmissionController,
    Engine,
    ReplicaPool,
    ServeError,
    ShedError,
    SwapController,
    TokenBucket,
)

IMG = (4, 4, 1)


def toy_fn(variables, images):
    flat = images.reshape((images.shape[0], -1))
    return {"scores": flat @ variables["w"],
            "mean": images.mean(axis=(1, 2, 3))}


def toy_variables(scale=1.0, seed=0):
    w = np.random.RandomState(seed).randn(16, 3).astype(np.float32) * scale
    return {"w": jnp.asarray(w)}


def images(n, seed=1):
    rng = np.random.RandomState(seed)
    return [rng.rand(*IMG).astype(np.float32) for _ in range(n)]


def build_engine_factory(registry, journal=None, buckets=(1, 2, 4)):
    def build(rid):
        eng = Engine(registry=registry, journal=journal)
        eng.register("toy", toy_fn, toy_variables(), input_shape=IMG,
                     buckets=buckets)
        return eng

    return build


def make_pool(journal=None, replicas=2, registry=None, **kw):
    registry = registry or Registry()
    kw.setdefault("max_wait_ms", 3.0)
    pool = ReplicaPool(build_engine_factory(registry, journal=journal),
                       replicas=replicas, journal=journal,
                       registry=registry, **kw)
    pool.start()
    return pool


def wait_all_serving(pool, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(s == "serving" for s in pool.replica_states().values()):
            return True
        time.sleep(0.02)
    return False


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.install(None)
    os.environ.pop(faults.ENV_SPEC, None)
    os.environ.pop(faults.ENV_SEED, None)


@pytest.fixture
def journal(tmp_path):
    j = RunJournal(str(tmp_path / "fleet.jsonl"), kind="serve")
    yield j
    if not j._closed:
        j.close()


def strict_errors(path):
    from tools.check_journal import check_journal

    return check_journal(path, strict=True)


# -- admission ---------------------------------------------------------------

class TestAdmission:
    def test_token_bucket_refill_math(self):
        t = {"now": 0.0}
        b = TokenBucket(rate_per_s=2.0, burst=3, clock=lambda: t["now"])
        assert [b.take() for _ in range(4)] == [True, True, True, False]
        t["now"] = 0.5  # one token refilled
        assert b.take() and not b.take()
        t["now"] = 100.0  # refill caps at burst
        assert [b.take() for _ in range(4)] == [True, True, True, False]

    def test_zero_rate_bucket_never_refills(self):
        b = TokenBucket(rate_per_s=0.0, burst=2, clock=lambda: 0.0)
        assert b.take() and b.take() and not b.take()

    def test_queue_bound_precedes_rate_budget(self):
        adm = AdmissionController(max_queue_depth=2, rate_per_s=0.0, burst=1)
        # a full queue must not spend a token on a doomed request
        assert adm.admit("toy", queue_depth=2) == "queue_full"
        assert adm.admit("toy", queue_depth=0) is None  # token spent here
        assert adm.admit("toy", queue_depth=0) == "rate_limited"

    def test_draining_sheds_everything(self):
        adm = AdmissionController(max_queue_depth=8)
        assert adm.admit("toy", 0) is None
        adm.start_draining()
        assert adm.admit("toy", 0) == "draining"

    def test_reasons_are_the_schema_enum(self):
        from tools.check_journal import SERVE_SHED_REASONS

        assert set(SHED_REASONS) == SERVE_SHED_REASONS

    def test_rejects_garbage(self):
        with pytest.raises(ValueError):
            AdmissionController(max_queue_depth=0)
        with pytest.raises(ValueError):
            TokenBucket(rate_per_s=1.0, burst=0)
        with pytest.raises(ValueError):
            TokenBucket(rate_per_s=-1.0, burst=1)


# -- engine hot-swap ---------------------------------------------------------

class TestEngineSwap:
    def _warmed(self, registry=None):
        eng = Engine(registry=registry or Registry())
        eng.register("toy", toy_fn, toy_variables(), input_shape=IMG,
                     buckets=(1, 2))
        eng.warmup()
        return eng

    def test_set_variables_serves_new_weights_without_compiling(self):
        eng = self._warmed()
        new = toy_variables(scale=3.0, seed=5)
        # the eager reference compiles its own op executables — baseline
        # AFTER it so the assertion isolates the swap + serving path
        ref = jax.device_get(toy_fn(new, jnp.asarray(np.stack(images(2)))))
        c0 = recompile_count()
        eng.set_variables("toy", new)
        out = jax.device_get(eng.run("toy", np.stack(images(2))))
        np.testing.assert_allclose(out["scores"], ref["scores"], rtol=1e-6)
        assert recompile_count() == c0

    def test_swap_refuses_aval_and_structure_changes(self):
        eng = self._warmed()
        with pytest.raises(ServeError, match="shape/dtype"):
            eng.set_variables("toy", {"w": jnp.zeros((8, 3), jnp.float32)})
        with pytest.raises(ServeError, match="tree structure"):
            eng.set_variables("toy", {"w": jnp.zeros((16, 3)),
                                      "extra": jnp.zeros(())})

    def test_clone_shares_executables(self):
        eng = self._warmed()
        new = toy_variables(scale=2.0, seed=9)
        # eager references first: they compile op executables of their
        # own and must not pollute the shadow's zero-compile assertion
        ref = jax.device_get(toy_fn(new, jnp.asarray(np.stack(images(2)))))
        ref_old = jax.device_get(
            toy_fn(toy_variables(), jnp.asarray(np.stack(images(2)))))
        c0 = recompile_count()
        shadow = eng.clone_with_variables({"toy": new})
        out = jax.device_get(shadow.run("toy", np.stack(images(2))))
        np.testing.assert_allclose(out["scores"], ref["scores"], rtol=1e-6)
        # the original keeps serving the OLD weights
        old = jax.device_get(eng.run("toy", np.stack(images(2))))
        np.testing.assert_allclose(old["scores"], ref_old["scores"],
                                   rtol=1e-6)
        assert recompile_count() == c0, "the shadow must be warm at birth"

    def test_clone_before_warmup_refused(self):
        eng = Engine(registry=Registry())
        eng.register("toy", toy_fn, toy_variables(), input_shape=IMG)
        with pytest.raises(ServeError, match="before warmup"):
            eng.clone_with_variables({"toy": toy_variables(seed=2)})


# -- pool routing + accounting -----------------------------------------------

class TestPoolRouting:
    def test_traffic_spreads_across_replicas(self, journal):
        pool = make_pool(journal=journal, replicas=2)
        try:
            futs = [pool.submit("toy", im) for im in images(16)]
            for f in futs:
                assert f.result(timeout=30) is not None
        finally:
            pool.close()
        journal.close()
        replicas = {e.get("replica") for e in read_journal(journal.path)
                    if e.get("event") == "serve_request"}
        assert replicas == {"r0", "r1"}, \
            "least-in-flight routing must use the whole fleet"
        assert strict_errors(journal.path) == []

    def test_pool_drain_aggregates_the_fleet_ledger(self, journal):
        pool = make_pool(journal=journal, replicas=2)
        futs = [pool.submit("toy", im) for im in images(6)]
        for f in futs:
            f.result(timeout=30)
        summary = pool.drain("close")
        assert summary["outcome"] == "flushed"
        assert summary["accepted"] == 6 and summary["completed"] == 6
        assert summary["offered"] == 6 and summary["shed"] == 0
        assert summary["replicas"] == 2
        # idempotent, and the pool's aggregated drain is the journal's
        # LAST serve_drain (obs_report's verdict row)
        assert pool.drain("close") is summary
        journal.close()
        drains = [e for e in read_journal(journal.path)
                  if e.get("event") == "serve_drain"]
        assert len(drains) == 3  # r0, r1, pool
        assert drains[-1].get("scope") == "pool"
        assert strict_errors(journal.path) == []

    def test_submit_before_start_and_after_drain(self):
        registry = Registry()
        pool = ReplicaPool(build_engine_factory(registry), replicas=1,
                           registry=registry)
        with pytest.raises(ServeError, match="before start"):
            pool.submit("toy", images(1)[0])
        pool.start()
        pool.close()
        # shutdown is an overload of size infinity: post-drain traffic
        # sheds by policy (typed, counted) instead of a bare refusal
        with pytest.raises(ShedError) as ei:
            pool.submit("toy", images(1)[0])
        assert ei.value.reason == "draining"

    def test_shed_determinism_under_seeded_arrivals(self, journal):
        # zero-refill token budget: the Nth request sheds no matter how
        # the scheduler interleaves — the seeded arrival pattern from
        # tools/loadgen.py reproduces the exact same shed set
        pool = make_pool(journal=journal, replicas=2,
                         admission=AdmissionController(
                             max_queue_depth=64, rate_per_s=0.0, burst=4))
        outcomes = []
        try:
            futs = []
            for im in images(10, seed=3):
                try:
                    futs.append(pool.submit("toy", im))
                    outcomes.append("admitted")
                except ShedError as e:
                    assert e.reason == "rate_limited"
                    outcomes.append("shed")
            for f in futs:
                f.result(timeout=30)
        finally:
            summary = pool.close()
        assert outcomes == ["admitted"] * 4 + ["shed"] * 6
        assert summary["shed"] == 6 and summary["accepted"] == 4
        assert summary["offered"] == 10
        journal.close()
        events = read_journal(journal.path)
        sheds = [e for e in events if e.get("event") == "serve_shed"]
        assert len(sheds) == 6
        assert all(e["reason"] == "rate_limited" for e in sheds)
        assert strict_errors(journal.path) == []

    def test_queue_full_sheds_when_inflight_exceeds_bound(self, journal):
        # a huge max-wait parks requests in the queue: in-flight depth
        # crosses the bound deterministically with no completions racing
        pool = make_pool(journal=journal, replicas=1, max_wait_ms=60_000,
                         admission=AdmissionController(max_queue_depth=2))
        try:
            futs = [pool.submit("toy", im) for im in images(2)]
            with pytest.raises(ShedError) as ei:
                pool.submit("toy", images(1)[0])
            assert ei.value.reason == "queue_full"
        finally:
            pool.close()
        for f in futs:
            assert f.done()

    def test_concurrent_submits_respect_the_queue_bound(self):
        import threading

        # 8 clients through the barrier at once against a depth-2 bound
        # with requests parked (huge max-wait, no completions racing):
        # the admission verdict and the in-flight increment are one
        # atomic step, so EXACTLY 2 admit no matter the interleaving
        pool = make_pool(replicas=1, max_wait_ms=60_000,
                         admission=AdmissionController(max_queue_depth=2))
        results = []
        res_lock = threading.Lock()
        barrier = threading.Barrier(8)

        def client(i):
            barrier.wait()
            try:
                fut = pool.submit("toy", images(1, seed=i)[0])
                with res_lock:
                    results.append(("ok", fut))
            except ShedError as e:
                with res_lock:
                    results.append(("shed", e.reason))

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        try:
            assert len([r for r in results if r[0] == "ok"]) == 2, results
            assert all(r[1] == "queue_full" for r in results
                       if r[0] == "shed")
        finally:
            pool.close()

    def test_slo_offered_vs_admitted_report(self):
        pool = make_pool(replicas=1,
                         admission=AdmissionController(
                             max_queue_depth=64, rate_per_s=0.0, burst=2))
        try:
            done = []
            for im in images(5):
                try:
                    done.append(pool.submit("toy", im))
                except ShedError:
                    pass
            for f in done:
                f.result(timeout=30)
            rep = pool.slo.report()["toy"]
            assert rep["offered"] == 5
            assert rep["shed"] == 3
            assert rep["admitted"] == 2
            assert rep["offered_rps"] >= rep["admitted_rps"] > 0
        finally:
            pool.close()


# -- replica death -----------------------------------------------------------

class TestReplicaDeath:
    def test_death_is_request_scoped_and_respawn_recovers(self, journal):
        pool = make_pool(journal=journal, replicas=2)
        c0 = recompile_count()
        try:
            faults.install_spec("serve.replica:io_error@1", seed=0,
                                journal=journal, export_env=False)
            futs = [pool.submit("toy", im) for im in images(6)]
            outcomes = []
            for f in futs:
                try:
                    f.result(timeout=30)
                    outcomes.append("ok")
                except ServeError:
                    outcomes.append("lost")
            faults.install(None)
            # SOME requests died with the replica, the rest were served
            # by the survivor — never the whole stream
            assert 1 <= outcomes.count("lost") < len(futs)
            assert wait_all_serving(pool), pool.replica_states()
            # the pool answers after recovery, on the SAME executables
            assert pool.submit(
                "toy", images(1)[0]).result(timeout=30) is not None
            assert recompile_count() == c0, \
                "respawn must reuse the surviving warmed engine"
        finally:
            summary = pool.close()
        assert summary["accepted"] == summary["completed"] \
            + summary["errors"] + summary["cancelled"]
        journal.close()
        events = read_journal(journal.path)
        lost = [e for e in events if e.get("event") == "replica_lost"]
        rec = [e for e in events if e.get("event") == "replica_recovered"]
        assert len(lost) == 1 and len(rec) == 1
        assert lost[0]["replica"] == rec[0]["replica"]
        assert lost[0]["attempt"] == 1 and rec[0]["attempt"] >= 1
        assert strict_errors(journal.path) == []

    def test_respawn_failure_retries_until_recovered(self, journal):
        # one replica, no concurrent traffic: the point's hit sequence is
        # exactly [death batch, respawn attempt 1, respawn attempt 2].
        # Rule one kills the replica, rule two (its own hit counter)
        # kills the FIRST respawn attempt — the RetryPolicy must back
        # off and recover on the second
        pool = make_pool(journal=journal, replicas=1)
        try:
            # two independent one-shot rules on the same point: hit 1 is
            # the death batch, hit 2 is the first respawn attempt
            faults.install_spec(
                "serve.replica:io_error@1;serve.replica:io_error@2",
                seed=0, journal=journal, export_env=False)
            fut = pool.submit("toy", images(1)[0])
            with pytest.raises(ServeError):
                fut.result(timeout=30)
            assert wait_all_serving(pool), pool.replica_states()
        finally:
            faults.install(None)
            pool.close()
        journal.close()
        rec = [e for e in read_journal(journal.path)
               if e.get("event") == "replica_recovered"]
        assert rec and rec[-1]["attempt"] >= 1

    def test_all_replicas_down_is_a_clear_error(self, journal):
        from deep_vision_tpu.resilience import RetryPolicy

        pool = make_pool(
            journal=journal, replicas=1,
            respawn_policy=RetryPolicy(
                name="serve.replica", max_attempts=1, base_delay_s=0.01,
                journal=journal, retry_on=(OSError, TimeoutError)))
        try:
            # every hit fires: the death AND the single respawn attempt
            faults.install_spec("serve.replica:io_error@0.999999", seed=1,
                                journal=journal, export_env=False)
            fut = pool.submit("toy", images(1)[0])
            with pytest.raises(ServeError):
                fut.result(timeout=30)
            deadline = time.time() + 10
            while time.time() < deadline and \
                    pool.replica_states()["r0"] != "dead":
                time.sleep(0.02)
            faults.install(None)
            assert pool.replica_states()["r0"] == "dead"
            with pytest.raises(ServeError, match="no serving replica"):
                pool.submit("toy", images(1)[0])
        finally:
            faults.install(None)
            summary = pool.close()
        # the dead replica's ledger folds into the pool totals exactly
        # ONCE (give-up already retired it; drain must not re-add), and
        # the unroutable request is refused, not silently admitted
        assert summary["accepted"] == 1 and summary["errors"] == 1
        assert summary["refused"] == 1
        assert summary["offered"] == summary["accepted"] \
            + summary["shed"] + summary["refused"]


# -- swap state machine ------------------------------------------------------

@pytest.fixture
def ckpt(tmp_path, journal):
    from deep_vision_tpu.core.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpt"), journal=journal)
    yield mgr
    mgr.close()


def drive_traffic_until(pool, predicate, timeout=30.0, seed=11):
    """Feed requests until predicate() (the swap needs live traffic for
    its canary verdict); returns the submitted futures."""
    rng = np.random.RandomState(seed)
    futs = []
    deadline = time.time() + timeout
    while time.time() < deadline and not predicate():
        try:
            futs.append(pool.submit(
                "toy", rng.rand(*IMG).astype(np.float32)))
        except Exception:
            pass
        time.sleep(0.004)
    return futs


class TestSwap:
    def _swap_setup(self, journal, ckpt, scale=2.0, poison=False):
        pool = make_pool(journal=journal, replicas=2)
        if poison:
            new = {"toy": {"w": jnp.full((16, 3), 1e38, jnp.float32)}}
        else:
            new = {"toy": toy_variables(scale=scale, seed=7)}
        ckpt.save_tree(1, new)
        ckpt.wait()
        swapper = SwapController(pool, journal=journal, canary_pct=50,
                                 min_canary_requests=4,
                                 canary_timeout_s=30.0)
        return pool, swapper, new

    def _swap_in_thread(self, swapper, ckpt):
        import threading

        box = {}

        def run():
            box["verdict"] = swapper.swap(ckpt, step=1, models=("toy",))

        t = threading.Thread(target=run, daemon=True)
        t.start()
        return t, box

    def test_promote_swaps_every_replica_zero_recompiles(self, journal,
                                                         ckpt):
        pool, swapper, new = self._swap_setup(journal, ckpt)
        try:
            c0 = recompile_count()
            t, box = self._swap_in_thread(swapper, ckpt)
            drive_traffic_until(pool, lambda: not t.is_alive())
            t.join(timeout=60)
            verdict = box["verdict"]
            assert verdict["outcome"] == "promoted", verdict
            assert recompile_count() == c0, \
                "the whole swap — restore, shadow warm, canary, promote " \
                "— must never touch the compiler"
            # every replica serves the new weights now
            im = images(1, seed=42)[0]
            ref = jax.device_get(toy_fn(new["toy"], jnp.asarray(im[None])))
            for _ in range(4):  # hits both replicas (least-in-flight)
                row = pool.submit("toy", im).result(timeout=30)
                np.testing.assert_allclose(row["scores"], ref["scores"][0],
                                           rtol=1e-5)
        finally:
            pool.close()
        journal.close()
        phases = [(e["phase"], e["outcome"])
                  for e in read_journal(journal.path)
                  if e.get("event") == "serve_swap"]
        assert phases == [("warm", "started"), ("warm", "ok"),
                          ("canary", "started"), ("canary", "ok"),
                          ("promote", "ok")]
        assert strict_errors(journal.path) == []

    def test_poisoned_canary_rolls_back(self, journal, ckpt):
        pool, swapper, _ = self._swap_setup(journal, ckpt, poison=True)
        try:
            t, box = self._swap_in_thread(swapper, ckpt)
            drive_traffic_until(pool, lambda: not t.is_alive())
            t.join(timeout=60)
            verdict = box["verdict"]
            assert verdict["outcome"] == "rolled_back", verdict
            assert verdict["reason"] == "errors"
            # the base replicas never stopped serving the OLD weights
            im = images(1, seed=43)[0]
            ref = jax.device_get(
                toy_fn(toy_variables(), jnp.asarray(im[None])))
            row = pool.submit("toy", im).result(timeout=30)
            np.testing.assert_allclose(row["scores"], ref["scores"][0],
                                       rtol=1e-5)
        finally:
            pool.close()
        journal.close()
        phases = [(e["phase"], e["outcome"])
                  for e in read_journal(journal.path)
                  if e.get("event") == "serve_swap"]
        assert ("canary", "failed") in phases
        assert ("rollback", "ok") in phases
        assert ("promote", "ok") not in phases
        assert strict_errors(journal.path) == []

    def test_failed_restore_rolls_back_at_warm(self, journal, ckpt):
        pool, swapper, _ = self._swap_setup(journal, ckpt)
        try:
            faults.install_spec("serve.replica:io_error@1", seed=0,
                                journal=journal, export_env=False)
            verdict = swapper.swap(ckpt, step=1, models=("toy",))
            faults.install(None)
            assert verdict["outcome"] == "rolled_back"
            assert verdict["reason"] == "warm_failed"
            # no canary was ever mounted; the pool is untouched
            assert pool.canary_status() is None
            assert pool.submit(
                "toy", images(1)[0]).result(timeout=30) is not None
        finally:
            pool.close()
        journal.close()
        phases = [(e["phase"], e["outcome"])
                  for e in read_journal(journal.path)
                  if e.get("event") == "serve_swap"]
        assert ("warm", "failed") in phases and ("rollback", "ok") in phases
        assert strict_errors(journal.path) == []

    def test_no_checkpoint_is_a_warm_failure(self, journal, ckpt):
        pool = make_pool(journal=journal, replicas=1)
        swapper = SwapController(pool, journal=journal)
        try:
            verdict = swapper.swap(ckpt, models=("toy",))
            assert verdict["outcome"] == "rolled_back"
            assert verdict["reason"] == "warm_failed"
        finally:
            pool.close()

    def test_enums_match_the_schema(self):
        from tools.check_journal import (
            SERVE_SWAP_OUTCOMES,
            SERVE_SWAP_PHASES,
        )

        assert set(SWAP_PHASES) == SERVE_SWAP_PHASES
        assert set(SWAP_OUTCOMES) == SERVE_SWAP_OUTCOMES


# -- locksmith-armed pool ----------------------------------------------------

class TestLocksmithArmed:
    def test_full_lifecycle_zero_violations(self, journal):
        # the runtime lock sanitizer across submit/route/death/respawn/
        # drain: the pool lock must never invert against the server's
        # submit/count locks or the queue condition
        locksmith.arm(journal=journal)
        try:
            pool = make_pool(journal=journal, replicas=2)
            faults.install_spec("serve.replica:io_error@2", seed=0,
                                journal=journal, export_env=False)
            futs = [pool.submit("toy", im) for im in images(12)]
            for f in futs:
                try:
                    f.result(timeout=30)
                except ServeError:
                    pass
            faults.install(None)
            wait_all_serving(pool)
            pool.close()
            report = locksmith.report()
            assert report["violations"] == [], report["violations"]
        finally:
            faults.install(None)
            locksmith.disarm()
        journal.close()
        assert not any(e.get("event") == "lock_order_violation"
                       for e in read_journal(journal.path))


# -- journal schema + report -------------------------------------------------

class TestFleetJournalSchema:
    def test_strict_accepts_fleet_events(self, tmp_path):
        j = RunJournal(str(tmp_path / "j.jsonl"), kind="serve")
        j.manifest()
        j.write("serve_shed", model="toy", reason="queue_full")
        j.write("serve_swap", swap=1, phase="warm", outcome="ok",
                compile_delta=0)
        j.write("serve_swap", swap=1, phase="canary", outcome="failed",
                canary_ok=3, canary_err=2)
        j.write("replica_lost", replica="r0", attempt=1,
                error="FaultInjected: boom")
        j.write("replica_recovered", replica="r0", attempt=2)
        j.close()
        assert strict_errors(j.path) == []

    def test_strict_rejects_bad_fleet_enums(self, tmp_path):
        path = str(tmp_path / "bad.jsonl")
        rows = [
            {"event": "serve_shed", "ts": 1.0, "run_id": "r",
             "model": "toy", "reason": "mood"},
            {"event": "serve_swap", "ts": 1.0, "run_id": "r",
             "phase": "yolo", "outcome": "ok"},
            {"event": "serve_swap", "ts": 1.0, "run_id": "r",
             "phase": "warm", "outcome": "perhaps"},
            {"event": "replica_lost", "ts": 1.0, "run_id": "r",
             "replica": 3, "attempt": "one"},
            {"event": "replica_recovered", "ts": 1.0, "run_id": "r",
             "replica": "r0"},
            {"event": "exit", "ts": 2.0, "run_id": "r", "status": "clean"},
        ]
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        errs = strict_errors(path)
        assert any("serve_shed reason" in e for e in errs)
        assert any("serve_swap phase" in e for e in errs)
        assert any("serve_swap outcome" in e for e in errs)
        assert any("replica_lost replica" in e for e in errs)
        assert any("replica_lost attempt" in e for e in errs)
        assert any("replica_recovered event missing field 'attempt'" in e
                   for e in errs)

    def test_obs_report_renders_fleet_section(self, tmp_path, capsys):
        from tools.obs_report import main as report_main

        j = RunJournal(str(tmp_path / "j.jsonl"), kind="serve")
        j.manifest()
        for rid, ms in (("r0", 2.0), ("r0", 3.0), ("r1", 4.0)):
            j.write("serve_request", model="toy", latency_ms=ms,
                    outcome="ok", replica=rid)
        j.write("serve_request", model="toy", latency_ms=1.0,
                outcome="error", replica="r1", error="ReplicaLost: died")
        j.write("replica_lost", replica="r1", attempt=1, error="x")
        j.write("replica_recovered", replica="r1", attempt=1)
        for _ in range(3):
            j.write("serve_shed", model="toy", reason="rate_limited")
        j.write("serve_shed", model="toy", reason="queue_full")
        j.write("serve_swap", swap=1, phase="warm", outcome="ok")
        j.write("serve_swap", swap=1, phase="canary", outcome="failed",
                canary_ok=1, canary_err=2, reason="errors")
        j.write("serve_swap", swap=1, phase="rollback", outcome="ok",
                reason="errors")
        j.write("serve_drain", reason="close", outcome="flushed",
                scope="pool", accepted=4, completed=3, errors=1,
                cancelled=0, pending=0, shed=4, offered=8, replicas=2)
        j.close()
        assert report_main([j.path]) == 0
        out = capsys.readouterr().out
        assert "replica r0" in out and "2 ok, 0 err" in out
        assert "lost x1 recovered x1" in out
        assert "pool latency" in out and "p99" in out
        assert "shed toy" in out and "queue_fullx1" in out \
            and "rate_limitedx3" in out
        assert "swap #1" in out and "canary failed" in out \
            and "rollback ok" in out
        assert "shed=4" in out and "offered=8" in out

    def test_obs_report_single_server_unchanged(self, tmp_path, capsys):
        from tools.obs_report import main as report_main

        j = RunJournal(str(tmp_path / "j.jsonl"), kind="serve")
        j.manifest()
        j.write("serve_request", model="toy", latency_ms=2.0, outcome="ok")
        j.write("serve_drain", reason="close", outcome="flushed",
                accepted=1, completed=1, errors=0, pending=0)
        j.close()
        assert report_main([j.path]) == 0
        out = capsys.readouterr().out
        assert "serving toy" in out
        assert "replica" not in out and "swap #" not in out
