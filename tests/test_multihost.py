"""Two-process jax.distributed smoke test (VERDICT r1 weak #7).

Launches two REAL processes that `jax.distributed.initialize` against a
local coordinator on the CPU backend (2 virtual devices each), build the
global mesh, assemble a host-sharded global batch, and psum across the whole
cluster — validating `parallel/multihost.py` beyond the single-process no-op
path. This is the closest a single machine gets to a DCN-connected pod:
process boundaries and the coordinator service are real, only the transport
is local.
"""
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # two real processes: excluded from the fast tier (`-m "not slow"`)

_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
pid = int(sys.argv[1])
import jax
import numpy as np

from deep_vision_tpu.parallel import multihost as mh

mh.initialize_distributed(
    coordinator_address="127.0.0.1:%PORT%", num_processes=2, process_id=pid
)
assert mh.process_count() == 2, mh.process_count()
assert mh.process_index() == pid
assert mh.is_primary() == (pid == 0)

mesh = mh.global_mesh()
assert mesh.shape["data"] == 4, mesh.shape  # 2 hosts x 2 virtual devices

# host-sharded input: this host contributes rows [2*pid, 2*pid+1]
shard_index, num_shards = mh.host_shard()
assert (shard_index, num_shards) == (pid, 2)
local = {"x": np.asarray([2.0 * pid, 2.0 * pid + 1.0], np.float32)}
gb = mh.form_global_array(local, mesh)
assert gb["x"].shape == (4,)

# a cluster-wide collective must see every host's rows: sum(0..3) == 6
from jax.sharding import NamedSharding, PartitionSpec as P

@jax.jit
def total(x):
    return jax.numpy.sum(x)

out = float(total(gb["x"]))
assert out == 6.0, out
assert mh.per_host_batch_size(8) == 4

mh.sync_hosts("test-barrier")

# preemption consensus: only host 0 raises the flag; BOTH must act on it
# (the trainer's SIGTERM path deadlocks if hosts disagree on the step)
assert mh.agree_flag(pid == 0) is True
assert mh.agree_flag(False) is False

print(f"proc {pid} OK total={out}")
"""


def test_two_process_distributed_psum(tmp_path):
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = _WORKER.replace("%PORT%", str(port))
    path = tmp_path / "worker.py"
    path.write_text(script)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(path), str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=150)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert f"proc {pid} OK total=6.0" in out


_FIT_WORKER = r"""
import os, sys, signal
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
pid = int(sys.argv[1]); ckdir = sys.argv[2]
import jax
import numpy as np
import jax.numpy as jnp

from deep_vision_tpu.parallel import multihost as mh

mh.initialize_distributed(
    coordinator_address="127.0.0.1:%PORT%", num_processes=2, process_id=pid
)
mesh = mh.global_mesh()  # data axis = 4 (2 hosts x 2 devices)

from deep_vision_tpu.core import CheckpointManager
from deep_vision_tpu.losses import classification_loss_fn
from deep_vision_tpu.models import get_model
from deep_vision_tpu.train import Trainer, build_optimizer

GLOBAL_BS = 16
STEPS_PER_EPOCH = 16

rng = np.random.RandomState(0)
images = rng.rand(GLOBAL_BS * STEPS_PER_EPOCH, 32, 32, 1).astype(np.float32) * 0.1
labels = rng.randint(0, 4, size=len(images))
for i, l in enumerate(labels):
    r, c = divmod(l, 2)
    images[i, r * 16:(r + 1) * 16, c * 16:(c + 1) * 16, 0] += 0.9
labels = labels.astype(np.int32)
half = mh.per_host_batch_size(GLOBAL_BS)
assert half == 8

def make():
    return Trainer(
        get_model("lenet5", num_classes=4), build_optimizer("adam", 1e-3),
        classification_loss_fn, sample_input=jnp.zeros((8, 32, 32, 1)),
        mesh=mesh, checkpoint_manager=CheckpointManager(ckdir),
    )

def train_data(trigger_preemption):
    def gen():
        for i in range(STEPS_PER_EPOCH):
            lo = i * GLOBAL_BS + pid * half
            local = {
                "image": images[lo:lo + half],
                "label": labels[lo:lo + half],
            }
            if trigger_preemption and i == 6 and pid == 1:
                # the "maintenance event" lands on ONE host only; consensus
                # must stop BOTH at the same optimizer-step boundary
                os.kill(os.getpid(), signal.SIGTERM)
            yield mh.form_global_array(local, mesh)
    return gen

trainer = make()
trainer.fit(train_data(True), epochs=2, preemption_poll_every=5)
step = int(trainer.state.step)
latest = trainer.ckpt.latest_step()
# SIGTERM before step 7; the next step-keyed poll is step 10: every host
# must have stopped and checkpointed exactly there
assert step == 10, step
assert latest == 10, latest

# resume on both hosts: the incomplete epoch re-runs, and the collectives
# stay aligned through a clean epoch after restore
t2 = make()
nxt = t2.resume()
assert nxt == 0, nxt
assert int(t2.state.step) == 10
t2.fit(train_data(False), epochs=1, start_epoch=nxt,
       preemption_poll_every=5)
assert int(t2.state.step) == 10 + STEPS_PER_EPOCH, int(t2.state.step)
assert t2.ckpt.latest_step() == 10 + STEPS_PER_EPOCH
print(f"proc {pid} PREEMPT-FIT OK step={int(t2.state.step)}")
"""


def test_two_process_fit_preemption_resume(tmp_path):
    """VERDICT r2 weak #4 / task: end-to-end Trainer.fit across two REAL
    processes with a one-sided SIGTERM mid-epoch. Both hosts must reach
    consensus at the same step-keyed boundary, checkpoint the same step,
    and resume through a clean epoch without collective misalignment."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = _FIT_WORKER.replace("%PORT%", str(port))
    path = tmp_path / "fit_worker.py"
    path.write_text(script)
    ckdir = tmp_path / "ckpt"
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(path), str(pid), str(ckdir)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-4000:]}"
        assert f"proc {pid} PREEMPT-FIT OK step=26" in out


_EVAL_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
pid = int(sys.argv[1])
import jax
import numpy as np
import jax.numpy as jnp

from deep_vision_tpu.parallel import multihost as mh

mh.initialize_distributed(
    coordinator_address="127.0.0.1:%PORT%", num_processes=2, process_id=pid
)
mesh = mh.global_mesh()  # data axis = 4 (2 hosts x 2 devices)

from deep_vision_tpu.losses import classification_loss_fn
from deep_vision_tpu.models import get_model
from deep_vision_tpu.train import Trainer, build_optimizer

# the same deterministic 24-sample eval set the parent scored single-process
rng = np.random.RandomState(7)
N = 24
images = rng.rand(N, 32, 32, 1).astype(np.float32) * 0.6
labels = rng.randint(0, 4, size=N)
for i, l in enumerate(labels):
    r, c = divmod(l, 2)
    images[i, r * 16:(r + 1) * 16, c * 16:(c + 1) * 16, 0] += 0.4
labels = labels.astype(np.int32)

trainer = Trainer(
    get_model("lenet5", num_classes=4), build_optimizer("adam", 1e-3),
    classification_loss_fn, sample_input=jnp.zeros((8, 32, 32, 1)),
    mesh=mesh,
)

GLOBAL_BS = 16
half = mh.per_host_batch_size(GLOBAL_BS)  # 8 rows per host per batch

def eval_batches():
    # batch 0: full 16; batch 1: 8 valid rows PADDED to 16 with a mask —
    # the uneven final shard every real eval set produces. Multi-host
    # padding happens before assembly (trainer._pad_and_mask docstring).
    for lo_g in (0, GLOBAL_BS):
        rows = min(GLOBAL_BS, N - lo_g)
        img = np.zeros((GLOBAL_BS, 32, 32, 1), np.float32)
        lab = np.zeros((GLOBAL_BS,), np.int32)
        msk = np.zeros((GLOBAL_BS,), np.float32)
        img[:rows] = images[lo_g:lo_g + rows]
        lab[:rows] = labels[lo_g:lo_g + rows]
        msk[:rows] = 1.0
        lo = pid * half
        local = {
            "image": img[lo:lo + half],
            "label": lab[lo:lo + half],
            "_mask": msk[lo:lo + half],
        }
        yield mh.form_global_array(local, mesh)

m = trainer.evaluate(eval_batches())
print(f"proc {pid} EVAL loss={m['loss']:.10f} top1={m['top1']:.10f} "
      f"top5={m['top5']:.10f}")
"""


def test_two_process_eval_metrics_match_single_process(tmp_path):
    """VERDICT r3 task 8: mAP/top-1-style metric aggregation over a
    host-sharded eval set (with an uneven, padded+masked final batch) must
    equal the single-process value exactly. Guards both the psum/weighting
    math and the valid-row weighting of padded final batches."""
    import socket

    import jax.numpy as jnp
    import numpy as np

    from deep_vision_tpu.losses import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train import Trainer, build_optimizer

    # single-process reference on this process's CPU mesh: identical data,
    # identical PRNGKey(0) init -> bitwise-identical params and logits
    rng = np.random.RandomState(7)
    N = 24
    images = rng.rand(N, 32, 32, 1).astype(np.float32) * 0.6
    labels = rng.randint(0, 4, size=N)
    for i, l in enumerate(labels):
        r, c = divmod(l, 2)
        images[i, r * 16:(r + 1) * 16, c * 16:(c + 1) * 16, 0] += 0.4
    labels = labels.astype(np.int32)
    ref_trainer = Trainer(
        get_model("lenet5", num_classes=4), build_optimizer("adam", 1e-3),
        classification_loss_fn, sample_input=jnp.zeros((8, 32, 32, 1)),
    )
    ref = ref_trainer.evaluate(iter(
        [{"image": images[i:i + 16], "label": labels[i:i + 16]}
         for i in range(0, N, 16)]
    ))

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = _EVAL_WORKER.replace("%PORT%", str(port))
    path = tmp_path / "eval_worker.py"
    path.write_text(script)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(path), str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
    got = {}
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-4000:]}"
        line = [ln for ln in out.splitlines()
                if ln.startswith(f"proc {pid} EVAL")][0]
        got[pid] = {kv.split("=")[0]: float(kv.split("=")[1])
                    for kv in line.split()[3:]}
    # both hosts agree with each other AND with the single-process value
    for key in ("loss", "top1", "top5"):
        assert got[0][key] == got[1][key], (key, got)
        np.testing.assert_allclose(got[0][key], ref[key], rtol=1e-5,
                                   err_msg=f"{key}: {got[0]} vs ref {ref}")
