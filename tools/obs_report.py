"""Render a run journal (obs/journal.py JSONL) into a per-run summary table.

    PYTHONPATH=. python tools/obs_report.py runs/resnet50.journal.jsonl [...]
    PYTHONPATH=. python tools/obs_report.py run.jsonl --trace run.trace.json

One table row block per run_id found in the files: manifest identity,
step-time/data-wait/examples-per-sec statistics (mean/p50/p90 from the
per-step events), recompile and HBM peaks, eval/checkpoint/bench events,
health findings (obs/health.py: non-finite steps, loss spikes, watchdog
hang dumps), and the terminal marker (clean exit vs crash vs
still-running). With `--trace`, a per-span time summary of the matching
Chrome trace (obs/trace.py) follows: total/mean/p50/p95/max wall ms per
span name — the "where did the time go" table without opening Perfetto.
With `--merged`, the input is a `tools/obs_merge.py` multi-host timeline
and the report shows per-host step statistics plus every detected
straggler. This is the diff surface for BENCH_* rounds: two journals
from different PRs summarize into directly comparable tables.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deep_vision_tpu.obs.journal import read_journal  # noqa: E402


def _percentile(xs: List[float], q: float) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    i = min(len(s) - 1, int(round(q * (len(s) - 1))))
    return s[i]


def _stats(xs: List[float]) -> Optional[dict]:
    if not xs:
        return None
    return {
        "n": len(xs),
        "mean": sum(xs) / len(xs),
        "p50": _percentile(xs, 0.5),
        "p90": _percentile(xs, 0.9),
        "max": max(xs),
    }


def summarize_run(events: List[dict]) -> dict:
    """Collapse one run's events into the report row dict."""
    out: dict = {"run_id": events[0].get("run_id", "?")}
    steps = [e for e in events if e.get("event") == "step"]
    manifest = next((e for e in events if e.get("event") == "run_manifest"), None)
    if manifest:
        out["kind"] = manifest.get("kind", "?")
        out["backend"] = manifest.get("backend", "?")
        out["devices"] = "%s x%s" % (
            manifest.get("device_kind", "?"), manifest.get("device_count", "?"))
        cfg = manifest.get("config") or {}
        if cfg:
            out["config"] = "%s (%s)" % (cfg.get("name", "?"), cfg.get("task", "?"))
        out["jax"] = manifest.get("jax_version", "?")
    out["steps"] = len(steps)
    for field in ("step_time_ms", "data_wait_ms", "examples_per_sec", "sync_ms"):
        st = _stats([float(e[field]) for e in steps if field in e])
        if st:
            out[field] = st
    recompiles = [int(e["recompiles"]) for e in steps if "recompiles" in e]
    if recompiles:
        out["recompiles"] = max(recompiles)
    # prefer the backend's true high-water (hbm_peak_bytes, stepclock
    # peak_bytes_in_use) over the max of sampled instantaneous values
    peak = [int(e["hbm_peak_bytes"]) for e in steps if "hbm_peak_bytes" in e]
    hbm = peak or [int(e["hbm_bytes"]) for e in steps if "hbm_bytes" in e]
    if hbm:
        out["hbm_peak_gb"] = max(hbm) / 1e9
    out["epochs"] = [e for e in events if e.get("event") == "epoch"]
    out["evals"] = [e for e in events if e.get("event") == "eval"]
    out["health"] = [e for e in events if e.get("event") == "health"]
    out["captures"] = [e for e in events
                       if e.get("event") == "profile_capture"]
    out["flight_dumps"] = [e for e in events
                           if e.get("event") == "flight_dump"]
    out["lock_violations"] = [e for e in events
                              if e.get("event") == "lock_order_violation"]
    out["lock_contention"] = [e for e in events
                              if e.get("event") == "lock_contention"]
    out["checkpoints"] = sum(
        1 for e in events if e.get("event") == "checkpoint" and e.get("saved"))
    out["benches"] = [e for e in events if e.get("event") == "bench"]
    serving = summarize_serving(events)
    if serving:
        out["serving"] = serving
    fleet_edge = summarize_fleet_edge(events)
    if fleet_edge:
        out["fleet_edge"] = fleet_edge
    data_plane = summarize_data_plane(events)
    if data_plane:
        out["data_plane"] = data_plane
    membership = summarize_membership(events)
    if membership:
        out["membership"] = membership
    cold_path = summarize_cold_path(events)
    if cold_path:
        out["cold_path"] = cold_path
    sharding = summarize_sharding(events)
    if sharding:
        out["sharding"] = sharding
    perf = summarize_perf(events)
    if perf:
        out["perf"] = perf
    goodput = summarize_goodput(events)
    if goodput:
        out["goodput"] = goodput
    alerts = summarize_alerts(events)
    if alerts:
        out["alerts"] = alerts
    terminal = next(
        (e for e in reversed(events) if e.get("event") in ("exit", "crash")),
        None)
    if terminal is None:
        out["status"] = "RUNNING-OR-KILLED (no terminal event)"
    elif terminal["event"] == "crash":
        out["status"] = "CRASHED: " + str(terminal.get("reason", ""))
    else:
        out["status"] = terminal.get("status", "clean_exit")
    first, last = events[0].get("ts"), events[-1].get("ts")
    if first is not None and last is not None:
        out["wall_s"] = float(last) - float(first)
    return out


def summarize_serving(events: List[dict]) -> Optional[dict]:
    """Collapse serve_* events (serve/router.py) into per-model serving
    rows: request counts, latency tail quantiles recomputed from the
    per-request events (exact, unlike the registry's bucket-resolution
    quantiles), batch occupancy and padding waste from the serve_batch
    aggregates, and the drain verdict. Fleet journals (serve/pool.py:
    replica-tagged requests, serve_shed / serve_swap / replica_lost
    events) additionally get per-replica ok/err rows, shed counts by
    reason, the swap timeline with the canary verdict, replica
    lost/recovered history, and pool-level latency tails recomputed
    exactly from the per-request events. None when the journal carries
    no serving traffic — training-only reports stay unchanged."""
    requests = [e for e in events if e.get("event") == "serve_request"]
    batches = [e for e in events if e.get("event") == "serve_batch"]
    drains = [e for e in events if e.get("event") == "serve_drain"]
    sheds = [e for e in events if e.get("event") == "serve_shed"]
    swaps = [e for e in events if e.get("event") == "serve_swap"]
    lost = [e for e in events if e.get("event") == "replica_lost"]
    recovered = [e for e in events if e.get("event") == "replica_recovered"]
    if not (requests or batches or drains or sheds or swaps or lost):
        return None
    models: Dict[str, dict] = {}

    def row_for(e):
        return models.setdefault(
            e.get("model", "?"),
            {"ok": 0, "error": 0, "rejected": 0, "cancelled": 0,
             "latencies": [], "slots": 0, "padded": 0, "batches": 0})

    for e in requests:
        m = row_for(e)
        outcome = e.get("outcome")
        # unknown outcomes (future producer / corrupt row) count as
        # errors rather than crashing the postmortem report — the strict
        # enum lives in check_journal, not here
        m[outcome if outcome in ("ok", "error", "rejected", "cancelled")
          else "error"] += 1
        if outcome == "ok" and isinstance(e.get("latency_ms"), (int, float)):
            m["latencies"].append(float(e["latency_ms"]))
    for e in batches:
        m = row_for(e)
        bucket, size = e.get("bucket"), e.get("size")
        if not isinstance(bucket, int) or not isinstance(size, int):
            continue  # corrupt/foreign row: never crash the postmortem
        m["batches"] += 1
        m["slots"] += bucket
        m["padded"] += max(0, bucket - size)
    out: dict = {"models": {}}
    for name, m in sorted(models.items()):
        row = {"ok": m["ok"], "error": m["error"], "rejected": m["rejected"],
               "cancelled": m["cancelled"], "batches": m["batches"]}
        if m["latencies"]:
            row.update(
                p50_ms=_percentile(m["latencies"], 0.5),
                p95_ms=_percentile(m["latencies"], 0.95),
                p99_ms=_percentile(m["latencies"], 0.99),
                mean_ms=sum(m["latencies"]) / len(m["latencies"]),
            )
        if m["slots"]:
            row["occupancy_pct"] = 100.0 * (m["slots"] - m["padded"]) \
                / m["slots"]
            row["padding_waste_pct"] = 100.0 * m["padded"] / m["slots"]
        out["models"][name] = row
    if drains:
        # the fleet verdict is the POOL's aggregated drain; a canary or
        # replica drain mid-run (swap promote/rollback writes one) must
        # not pose as the shutdown verdict in a crashed-run postmortem
        pool_drains = [e for e in drains if e.get("scope") == "pool"]
        last = (pool_drains or drains)[-1]
        out["drain"] = {k: last.get(k) for k in
                        ("reason", "outcome", "accepted", "completed",
                         "errors", "cancelled", "pending", "shed",
                         "offered", "refused", "replicas")
                        if last.get(k) is not None}
    fleet = summarize_fleet(requests, sheds, swaps, lost, recovered)
    if fleet:
        out["fleet"] = fleet
    return out


def summarize_fleet_edge(events: List[dict]) -> Optional[dict]:
    """The front door's view (serve/transport.py journal events): the
    status-code ledger across every transport_request, outcome counts
    with the offered == sum-of-outcomes balance verdict, deadline sheds
    split by stage (admission vs dispatch — WHERE the budget died), the
    latency tail of the 200s recomputed exactly, and each endpoint's
    lifecycle. None when the journal carries no transport events —
    in-process serving reports render byte-unchanged."""
    requests = [e for e in events if e.get("event") == "transport_request"]
    servers = [e for e in events if e.get("event") == "transport_server"]
    if not (requests or servers):
        return None
    out: dict = {}
    if requests:
        by_status: Dict[str, int] = {}
        outcomes: Dict[str, int] = {}
        deadline_stages: Dict[str, int] = {}
        latencies: List[float] = []
        for e in requests:
            st = e.get("status")
            by_status[str(st)] = by_status.get(str(st), 0) + 1
            oc = str(e.get("outcome", "?"))
            outcomes[oc] = outcomes.get(oc, 0) + 1
            if oc == "deadline":
                stage = str(e.get("stage", "?"))
                deadline_stages[stage] = deadline_stages.get(stage, 0) + 1
            if st == 200 and isinstance(e.get("latency_ms"), (int, float)):
                latencies.append(float(e["latency_ms"]))
        out["requests"] = {
            "offered": len(requests),
            "by_status": {k: by_status[k] for k in sorted(by_status)},
            "outcomes": {k: outcomes[k] for k in sorted(outcomes)},
            # every journaled request carries exactly one outcome, so
            # the wire ledger balances by construction — a False here
            # means a truncated/hand-edited journal
            "balanced": len(requests) == sum(outcomes.values()),
        }
        if deadline_stages:
            out["deadline_stages"] = deadline_stages
        if latencies:
            out["latency"] = {
                "n": len(latencies),
                "p50_ms": _percentile(latencies, 0.5),
                "p99_ms": _percentile(latencies, 0.99),
            }
    if servers:
        eps: Dict[str, dict] = {}
        for e in servers:
            key = f"{e.get('host', '?')}:{e.get('port', '?')}"
            row = eps.setdefault(key, {"started": 0, "stopped": 0,
                                       "failed": 0})
            oc = e.get("outcome")
            if oc in row:
                row[oc] += 1
        out["servers"] = eps
    return out


def summarize_data_plane(events: List[dict]) -> Optional[dict]:
    """The data-plane view (data/snapshot.py + data/service.py events):
    service throughput and reconnects from the `data_service` role
    summaries, worker lost/recovered history, and the `data_resume`
    verdict — the "did the input pipeline resume where the model did"
    answer. None when the journal carries no data-plane events, so
    every existing report renders unchanged."""
    resumes = [e for e in events if e.get("event") == "data_resume"]
    lost = [e for e in events if e.get("event") == "data_worker_lost"]
    recovered = [e for e in events
                 if e.get("event") == "data_worker_recovered"]
    summaries = [e for e in events if e.get("event") == "data_service"]
    if not (resumes or lost or recovered or summaries):
        return None
    out: dict = {}
    if resumes:
        out["resumes"] = [
            {k: e.get(k) for k in
             ("verdict", "epoch", "batches", "shard", "record")
             if e.get(k) is not None}
            for e in resumes]
    roles: Dict[str, dict] = {}
    for e in summaries:
        role = str(e.get("role", "?"))
        row = roles.setdefault(role, {"batches": 0, "reconnects": 0,
                                      "workers_lost": 0,
                                      "workers_recovered": 0, "n": 0})
        row["n"] += 1
        for k in ("batches", "reconnects", "workers_lost",
                  "workers_recovered"):
            if isinstance(e.get(k), int):
                row[k] += e[k]
    if roles:
        out["service"] = roles
    if lost or recovered:
        out["workers"] = {"lost": len(lost), "recovered": len(recovered)}
    return out


def summarize_membership(events: List[dict]) -> Optional[dict]:
    """The host-membership timeline (resilience/rendezvous.py events):
    generation history from `world_resized`, per-host loss/join rows
    with lease gaps from `host_lost`/`host_joined`, and the data-plane
    reshards that followed. None when the journal carries no membership
    events — every existing report renders byte-unchanged."""
    lost = [e for e in events if e.get("event") == "host_lost"]
    joined = [e for e in events if e.get("event") == "host_joined"]
    resized = [e for e in events if e.get("event") == "world_resized"]
    reshards = [e for e in events if e.get("event") == "data_reshard"]
    if not (lost or joined or resized or reshards):
        return None
    out: dict = {}
    if resized:
        out["generations"] = [
            {k: e.get(k) for k in
             ("generation", "from", "to", "resume_step", "ts")
             if e.get(k) is not None}
            for e in resized]
    if lost:
        out["lost"] = [
            {k: e.get(k) for k in ("host", "generation", "lease_gap_s", "ts")
             if e.get(k) is not None}
            for e in lost]
    if joined:
        out["joined"] = [
            {k: e.get(k) for k in ("host", "generation", "ts")
             if e.get(k) is not None}
            for e in joined]
    if reshards:
        out["reshards"] = [
            {k: e.get(k) for k in
             ("generation", "from", "to", "shard_index", "num_shards")
             if e.get(k) is not None}
            for e in reshards]
    return out


def summarize_cold_path(events: List[dict]) -> Optional[dict]:
    """The executable-cache / quantization view (core/excache.py +
    serve/quantize.py events): hit/miss/store/invalid counts with the
    invalid reasons spelled out, plus each calibration verdict. None
    when the journal carries no cold-path events — training-only and
    pre-cache serving reports render byte-unchanged."""
    hits = [e for e in events if e.get("event") == "excache_hit"]
    misses = [e for e in events if e.get("event") == "excache_miss"]
    stores = [e for e in events if e.get("event") == "excache_store"]
    invalid = [e for e in events if e.get("event") == "excache_invalid"]
    quants = [e for e in events if e.get("event") == "quant_calibrated"]
    if not (hits or misses or stores or invalid or quants):
        return None
    out: dict = {"hits": len(hits), "misses": len(misses),
                 "stores": len(stores), "invalid": len(invalid)}
    if invalid:
        by_reason: dict = {}
        for e in invalid:
            r = str(e.get("reason", "?"))
            by_reason[r] = by_reason.get(r, 0) + 1
        out["invalid_reasons"] = by_reason
    if quants:
        out["quant"] = [
            {"model": e.get("model", "?"),
             "metric": e.get("metric", "?"),
             "delta": e.get("delta"),
             "tolerance": e.get("tolerance"),
             "accepted": bool(e.get("accepted"))}
            for e in quants]
    return out


def summarize_sharding(events: List[dict]) -> Optional[dict]:
    """The declarative-sharding view (parallel/shardmap.py): each
    `sharding_resolved` event's coverage ledger (matched/unmatched,
    sharded vs replicated float leaves, the mesh it resolved on) with
    the top rule hit counts, plus scaling-efficiency rows when the
    journal carries a MULTICHIP bench event (`bench.py --multichip` /
    tools/scaling.py rows, recognized by their data+efficiency keys).
    None when the journal has neither — every existing report renders
    byte-unchanged."""
    resolved = [e for e in events if e.get("event") == "sharding_resolved"]
    scaling: List[dict] = []
    for e in events:
        if e.get("event") != "bench":
            continue
        rows = (e.get("result") or {}).get("rows")
        if isinstance(rows, list) and rows and all(
                isinstance(r, dict) and "data" in r and "efficiency" in r
                for r in rows):
            scaling.extend(rows)
    if not (resolved or scaling):
        return None
    out: dict = {}
    if resolved:
        tables = []
        for e in resolved:
            row = {k: e.get(k) for k in
                   ("model", "matched", "unmatched", "sharded_leaves",
                    "replicated", "float_leaves", "mesh", "dropped_dims")
                   if e.get(k) is not None}
            rules = e.get("rules")
            if isinstance(rules, dict):
                hits = [(p, n) for p, n in rules.items()
                        if isinstance(n, int) and n > 0]
                hits.sort(key=lambda pn: -pn[1])
                row["top_rules"] = hits[:5]
            paths = e.get("unmatched_paths")
            if isinstance(paths, list) and paths:
                row["unmatched_paths"] = [str(p) for p in paths[:5]]
            tables.append(row)
        out["tables"] = tables
    if scaling:
        out["scaling"] = scaling
    return out


def summarize_perf(events: List[dict]) -> Optional[dict]:
    """The performance-attribution view (obs/perfwatch.py +
    tools/perf_gate.py events): one row per profiled jit pair with its
    XLA cost analysis and collective roll-up, the per-(kind, dtype)
    collective inventory under it, and every gate breach with the
    baseline/threshold it broke. None when the journal carries no perf
    events — every existing report renders byte-unchanged."""
    profiles = [e for e in events if e.get("event") == "perf_profile"]
    collectives = [e for e in events if e.get("event") == "perf_collective"]
    regressions = [e for e in events if e.get("event") == "perf_regression"]
    if not (profiles or collectives or regressions):
        return None
    out: dict = {}
    if profiles:
        pairs = []
        for e in profiles:
            row = {k: e.get(k) for k in
                   ("name", "flops", "bytes_accessed", "temp_bytes",
                    "collective_count", "collective_bytes", "source")
                   if e.get(k) is not None}
            row["collectives"] = [
                {k: c.get(k) for k in
                 ("kind", "dtype", "ops", "bytes", "group_size")
                 if c.get(k) is not None}
                for c in collectives if c.get("name") == e.get("name")]
            pairs.append(row)
        out["pairs"] = pairs
    if regressions:
        out["regressions"] = [
            {k: e.get(k) for k in
             ("metric", "baseline", "observed", "threshold", "direction")
             if e.get(k) is not None}
            for e in regressions]
    return out


def summarize_goodput(events: List[dict]) -> Optional[dict]:
    """The wall-clock attribution view (obs/goodput.py events): the
    terminal `goodput_summary` when the run wrote one (the meter's
    closer guarantees it on any journal'd exit), else the running total
    accumulated over `goodput_interval` rows (a SIGKILLed run leaves
    only those). The imbalance flag marks an accounting leak — buckets
    that do not sum to wall clock within 2%. None when the journal
    carries no goodput events, so every pre-goodput report renders
    byte-unchanged."""
    summaries = [e for e in events if e.get("event") == "goodput_summary"]
    intervals = [e for e in events if e.get("event") == "goodput_interval"]
    if not (summaries or intervals):
        return None
    if summaries:
        last = summaries[-1]
        buckets = {k: float(v) for k, v in (last.get("buckets") or {}).items()
                   if isinstance(v, (int, float))}
        return {"source": "summary",
                "wall_s": float(last.get("wall_s", 0.0) or 0.0),
                "goodput_frac": float(last.get("goodput_frac", 0.0) or 0.0),
                "imbalance_frac": float(
                    last.get("imbalance_frac", 0.0) or 0.0),
                "buckets": buckets}
    buckets = {}
    wall = 0.0
    for e in intervals:
        wall += float(e.get("dur_s", 0.0) or 0.0)
        for k, v in (e.get("buckets") or {}).items():
            if isinstance(v, (int, float)):
                buckets[k] = buckets.get(k, 0.0) + float(v)
    total = sum(buckets.values())
    return {"source": "intervals",
            "wall_s": wall,
            "goodput_frac": (buckets.get("productive_step", 0.0) / wall
                             if wall > 0 else 0.0),
            "imbalance_frac": (abs(wall - total) / wall if wall > 0
                               else 0.0),
            "buckets": buckets}


def summarize_alerts(events: List[dict]) -> Optional[dict]:
    """The burn-rate alert timeline (obs/alerts.py events): each
    `alert_fired` paired FIFO-per-rule with its `alert_resolved`, plus
    any alert still firing when the journal ended. None when the journal
    carries no alert events — alert-free reports render byte-unchanged."""
    fired = [e for e in events if e.get("event") == "alert_fired"]
    resolved = [e for e in events if e.get("event") == "alert_resolved"]
    if not (fired or resolved):
        return None
    open_by_rule: Dict[str, List[dict]] = {}
    episodes: List[dict] = []
    for e in fired:
        row = {k: e.get(k) for k in
               ("rule", "severity", "value", "threshold", "window_s")
               if e.get(k) is not None}
        row["fired_ts"] = e.get("ts")
        episodes.append(row)
        open_by_rule.setdefault(str(e.get("rule", "?")), []).append(row)
    for e in resolved:
        q = open_by_rule.get(str(e.get("rule", "?")))
        if q:
            row = q.pop(0)
            row["resolved_ts"] = e.get("ts")
            if isinstance(e.get("dur_s"), (int, float)):
                row["dur_s"] = float(e["dur_s"])
    return {"episodes": episodes,
            "still_firing": sum(1 for r in episodes
                                if "resolved_ts" not in r)}


def summarize_fleet(requests: List[dict], sheds: List[dict],
                    swaps: List[dict], lost: List[dict],
                    recovered: List[dict]) -> Optional[dict]:
    """The per-replica / swap-timeline view of a fleet journal
    (serve/pool.py). None when nothing carries a replica tag and no
    fleet events exist — single-server journals render exactly as
    before."""
    replicas: Dict[str, dict] = {}

    def replica_row(rid):
        return replicas.setdefault(
            rid, {"ok": 0, "error": 0, "rejected": 0, "cancelled": 0,
                  "lost": 0, "recovered": 0})

    for e in requests:
        rid = e.get("replica")
        if not isinstance(rid, str):
            continue
        row = replica_row(rid)
        outcome = e.get("outcome")
        row[outcome if outcome in ("ok", "error", "rejected", "cancelled")
            else "error"] += 1
    for key, events in (("lost", lost), ("recovered", recovered)):
        for e in events:
            if isinstance(e.get("replica"), str):
                replica_row(e["replica"])[key] += 1
    shed_rows: Dict[str, Dict[str, int]] = {}
    for e in sheds:
        by_reason = shed_rows.setdefault(str(e.get("model", "?")), {})
        reason = str(e.get("reason", "?"))
        by_reason[reason] = by_reason.get(reason, 0) + 1
    timelines: Dict[int, List[dict]] = {}
    for e in swaps:
        sid = e.get("swap")
        sid = sid if isinstance(sid, int) else 0
        timelines.setdefault(sid, []).append(
            {k: e.get(k) for k in
             ("phase", "outcome", "reason", "error", "canary_ok",
              "canary_err", "error_rate", "p99_ms", "pct", "replica")
             if e.get(k) is not None})
    if not (replicas or shed_rows or timelines):
        return None
    out: dict = {}
    if replicas:
        out["replicas"] = {rid: replicas[rid] for rid in sorted(replicas)}
        # the pool-level tail across every replica and model: the number
        # an operator pages on, exact from the per-request events
        lat = [float(e["latency_ms"]) for e in requests
               if e.get("outcome") == "ok"
               and isinstance(e.get("latency_ms"), (int, float))]
        if lat:
            out["pool_latency"] = {
                "n": len(lat),
                "p50_ms": _percentile(lat, 0.5),
                "p95_ms": _percentile(lat, 0.95),
                "p99_ms": _percentile(lat, 0.99),
            }
    if shed_rows:
        out["shed"] = shed_rows
    if timelines:
        out["swaps"] = [timelines[sid] for sid in sorted(timelines)]
    return out


def _fmt_stat(st: dict, unit: str = "") -> str:
    return (f"mean {st['mean']:.2f}{unit}  p50 {st['p50']:.2f}{unit}  "
            f"p90 {st['p90']:.2f}{unit}  max {st['max']:.2f}{unit}  "
            f"(n={st['n']})")


def render(summary: dict) -> str:
    rows = [("run", summary["run_id"]),
            ("status", summary["status"])]
    for k in ("kind", "config", "backend", "devices", "jax"):
        if k in summary:
            rows.append((k, summary[k]))
    if "wall_s" in summary:
        rows.append(("wall clock", f"{summary['wall_s']:.1f} s"))
    rows.append(("steps", str(summary["steps"])))
    for field, unit in (("step_time_ms", " ms"), ("data_wait_ms", " ms"),
                        ("sync_ms", " ms"), ("examples_per_sec", "")):
        if field in summary:
            rows.append((field, _fmt_stat(summary[field], unit)))
    if "recompiles" in summary:
        rows.append(("recompiles", str(summary["recompiles"])))
    if "hbm_peak_gb" in summary:
        rows.append(("hbm peak", f"{summary['hbm_peak_gb']:.2f} GB"))
    if summary["checkpoints"]:
        rows.append(("checkpoints", str(summary["checkpoints"])))
    for e in summary["epochs"]:
        parts = " ".join(f"{k}={v:.4f}" for k, v in
                         (e.get("summary") or {}).items()
                         if isinstance(v, (int, float)))
        label = f"epoch {e.get('epoch')}"
        if e.get("name"):
            label += f" [{e['name']}]"
        rows.append((label, parts))
    for e in summary["evals"]:
        parts = " ".join(f"{k}={v:.4f}" for k, v in
                         (e.get("summary") or {}).items()
                         if isinstance(v, (int, float)))
        rows.append((f"eval e{e.get('epoch')}", parts))
    for e in summary["benches"]:
        res = e.get("result") or {}
        parts = " ".join(f"{k}={v}" for k, v in res.items()
                         if isinstance(v, (int, float)))
        rows.append((f"bench {e.get('name')}", parts))
    # serving summary (serve/router.py journal events): one row per
    # model, then the drain verdict — the SLO table without a live
    # registry endpoint
    serving = summary.get("serving")
    if serving:
        for name, r in serving["models"].items():
            parts = f"{r['ok']} ok, {r['error']} err"
            if r.get("rejected"):
                parts += f", {r['rejected']} rejected"
            if r.get("cancelled"):
                parts += f", {r['cancelled']} cancelled"
            if "p50_ms" in r:
                parts += (f"  latency p50 {r['p50_ms']:.2f}ms "
                          f"p95 {r['p95_ms']:.2f}ms "
                          f"p99 {r['p99_ms']:.2f}ms")
            if r.get("batches"):
                parts += f"  batches {r['batches']}"
            if "occupancy_pct" in r:
                parts += (f"  occupancy {r['occupancy_pct']:.1f}%"
                          f"  padding waste {r['padding_waste_pct']:.1f}%")
            rows.append((f"serving {name}", parts))
        # fleet view (serve/pool.py journals): per-replica ledgers, the
        # pool-level tail, shed-by-reason, and each swap's timeline —
        # the 3am "which replica / which swap / how much shed" answers
        fleet = serving.get("fleet")
        if fleet:
            for rid, r in fleet.get("replicas", {}).items():
                parts = f"{r['ok']} ok, {r['error']} err"
                if r.get("cancelled"):
                    parts += f", {r['cancelled']} cancelled"
                if r.get("lost"):
                    parts += (f"  lost x{r['lost']}"
                              f" recovered x{r['recovered']}")
                rows.append((f"replica {rid}", parts))
            pl = fleet.get("pool_latency")
            if pl:
                rows.append(("pool latency",
                             f"p50 {pl['p50_ms']:.2f}ms "
                             f"p95 {pl['p95_ms']:.2f}ms "
                             f"p99 {pl['p99_ms']:.2f}ms "
                             f"(n={pl['n']} admitted ok)"))
            for model, by_reason in fleet.get("shed", {}).items():
                total = sum(by_reason.values())
                detail = " ".join(f"{k}x{n}"
                                  for k, n in sorted(by_reason.items()))
                rows.append((f"shed {model}", f"{total} ({detail})"))
            for i, timeline in enumerate(fleet.get("swaps", []), 1):
                steps = []
                verdict = ""
                for t in timeline:
                    if t.get("outcome") == "started":
                        continue  # the terminal outcome per phase tells it
                    steps.append(f"{t.get('phase')} {t.get('outcome')}")
                    if t.get("phase") == "canary" and "canary_ok" in t:
                        verdict = (f"  [canary {t['canary_ok']} ok, "
                                   f"{t.get('canary_err', 0)} err"
                                   + (f", p99 {t['p99_ms']:.1f}ms"
                                      if isinstance(t.get("p99_ms"),
                                                    (int, float)) else "")
                                   + "]")
                    if t.get("reason"):
                        steps[-1] += f" ({t['reason']})"
                rows.append((f"swap #{i}", " -> ".join(steps) + verdict))
        drain = serving.get("drain")
        if drain:
            parts = (f"accepted={drain.get('accepted')} "
                     f"completed={drain.get('completed')} "
                     f"errors={drain.get('errors')}")
            if drain.get("cancelled"):
                parts += f" cancelled={drain['cancelled']}"
            if drain.get("shed"):
                parts += f" shed={drain['shed']}"
            if drain.get("offered"):
                parts += f" offered={drain['offered']}"
            rows.append(("serve drain",
                         f"{drain.get('reason')} -> {drain.get('outcome')} "
                         f"({parts} pending={drain.get('pending')})"))
    # the fleet edge (serve/transport.py): what the WIRE saw — the
    # status-code ledger, where deadlines died, and the socket tail
    fleet_edge = summary.get("fleet_edge")
    if fleet_edge:
        req = fleet_edge.get("requests")
        if req:
            codes = " ".join(f"{k}x{v}"
                             for k, v in req["by_status"].items())
            rows.append(("fleet edge",
                         f"{req['offered']} request(s) over the wire "
                         f"[{codes}]"
                         + ("" if req.get("balanced")
                            else "  LEDGER IMBALANCED")))
            oc = req.get("outcomes", {})
            shedlike = {k: v for k, v in oc.items()
                        if k in ("shed", "deadline", "torn", "bad_request")
                        and v}
            if shedlike:
                rows.append(("  edge outcomes",
                             " ".join(f"{k}={v}"
                                      for k, v in sorted(
                                          shedlike.items()))))
        stages = fleet_edge.get("deadline_stages")
        if stages:
            rows.append(("  deadline shed",
                         " ".join(f"{k}={v}" for k, v in
                                  sorted(stages.items()))
                         + "  (admission = never queued; dispatch = "
                         "queued but expired before its batch)"))
        lat = fleet_edge.get("latency")
        if lat:
            rows.append(("  edge latency",
                         f"p50 {lat['p50_ms']:.1f}ms  "
                         f"p99 {lat['p99_ms']:.1f}ms  (n={lat['n']})"))
        for ep, r in sorted(fleet_edge.get("servers", {}).items()):
            life = f"started x{r['started']}, stopped x{r['stopped']}"
            if r.get("failed"):
                life += f", FAILED x{r['failed']}"
            rows.append((f"  endpoint {ep}", life))
    # data plane (data/snapshot.py + data/service.py): service
    # throughput/reconnects, worker death history, and the resume
    # verdict — whether the input stream continued where the model did
    data_plane = summary.get("data_plane")
    if data_plane:
        for role, r in sorted(data_plane.get("service", {}).items()):
            parts = f"{r['batches']} batches"
            if role == "client" and r.get("reconnects"):
                parts += f", {r['reconnects']} reconnect(s)"
            if role == "server" and (r.get("workers_lost")
                                     or r.get("workers_recovered")):
                parts += (f", workers lost x{r['workers_lost']}"
                          f" recovered x{r['workers_recovered']}")
            rows.append((f"data service [{role}]", parts))
        w = data_plane.get("workers")
        if w and "service" not in data_plane:
            rows.append(("data workers",
                         f"lost x{w['lost']} recovered x{w['recovered']}"))
        for e in data_plane.get("resumes", []):
            detail = (f"epoch {e.get('epoch')} batch {e.get('batches')}"
                      if e.get("verdict") == "restored" else "from scratch")
            if e.get("shard"):
                detail += f" (shard {os.path.basename(str(e['shard']))})"
            rows.append(("data resume", f"{e.get('verdict')} ({detail})"))
    # host-membership timeline (resilience/rendezvous.py): which hosts
    # died at which generation (and how stale their lease was), each
    # world resize with its resume step, and the input-pipeline reshards
    # that followed — the 3am "why is this run suddenly world 2" answer
    membership = summary.get("membership")
    if membership:
        for e in membership.get("generations", []):
            detail = (f"world {e.get('from', '?')} -> {e.get('to', '?')}"
                      f" at generation {e.get('generation', '?')}")
            rs = e.get("resume_step")
            if isinstance(rs, int) and rs >= 0:
                detail += f", resume step {rs}"
            elif rs is not None:
                detail += ", no checkpoint to resume"
            rows.append(("membership", detail))
        for e in membership.get("lost", []):
            detail = f"at generation {e.get('generation', '?')}"
            if isinstance(e.get("lease_gap_s"), (int, float)):
                detail += f" (lease gap {e['lease_gap_s']:.1f}s)"
            rows.append((f"  host_lost {e.get('host', '?')}", detail))
        for e in membership.get("joined", []):
            rows.append((f"  host_joined {e.get('host', '?')}",
                         f"at generation {e.get('generation', '?')}"))
        for e in membership.get("reshards", []):
            rows.append(("  data_reshard",
                         f"hosts {e.get('from', '?')} -> {e.get('to', '?')}"
                         f", this host now shard "
                         f"{e.get('shard_index', '?')}/"
                         f"{e.get('num_shards', '?')}"))
    # cold path (core/excache.py + serve/quantize.py): cache hit/miss/
    # store accounting with refused entries by reason, and each int8
    # calibration verdict — the "did this restart pay the compiler"
    # and "is the int8 engine inside its gate" answers
    cold = summary.get("cold_path")
    if cold:
        parts = (f"{cold['hits']} hit, {cold['misses']} miss, "
                 f"{cold['stores']} stored")
        if cold["invalid"]:
            reasons = ", ".join(f"{n} {r}" for r, n in
                                sorted(cold["invalid_reasons"].items()))
            parts += f", {cold['invalid']} refused ({reasons})"
        rows.append(("executable cache", parts))
        for q in cold.get("quant", []):
            verdict = "accepted" if q["accepted"] else "REFUSED"
            detail = f"{q['metric']} delta {q['delta']}"
            if q.get("tolerance") is not None:
                detail += f" (tolerance {q['tolerance']})"
            rows.append((f"  int8 {q['model']}", f"{verdict}: {detail}"))
    # declarative sharding (parallel/shardmap.py sharding_resolved +
    # bench.py --multichip): which table resolved, how many leaves each
    # rule claimed, what actually sharded, and the scaling-efficiency
    # curve — the "is the parallelism real and what does it buy" answers
    sharding = summary.get("sharding")
    if sharding:
        for t in sharding.get("tables", []):
            mesh = t.get("mesh") or {}
            mesh_s = ",".join(f"{k}={v}" for k, v in mesh.items())
            parts = (f"{t.get('sharded_leaves', '?')} sharded / "
                     f"{t.get('replicated', '?')} replicated of "
                     f"{t.get('float_leaves', '?')} float leaves "
                     f"(mesh {mesh_s})")
            if t.get("unmatched"):
                parts += f"  {t['unmatched']} catch-all-only"
            if t.get("dropped_dims"):
                parts += f"  {t['dropped_dims']} dims dropped"
            rows.append((f"sharding {t.get('model', '?')}", parts))
            for pat, n in t.get("top_rules", []):
                rows.append(("  rule", f"{pat} -> {n} leaves"))
            for p in t.get("unmatched_paths", []):
                rows.append(("  catch-all", p))
        for r in sharding.get("scaling", []):
            rows.append((f"scaling data={r.get('data')}",
                         f"{r.get('examples_per_sec')} ex/s  "
                         f"{r.get('per_device_examples_per_sec')} /device  "
                         f"efficiency {r.get('efficiency')}"))
    # performance attribution (obs/perfwatch.py + tools/perf_gate.py):
    # what each compiled jit pair costs (XLA cost analysis), which
    # collectives the partitioner gave it, and any gate breach with the
    # baseline it broke — the "why is this PR slower" paper trail
    perf = summary.get("perf")
    if perf:
        for pr in perf.get("pairs", []):
            parts = []
            if pr.get("flops") is not None:
                parts.append(f"flops {pr['flops']:.3g}")
            if pr.get("bytes_accessed") is not None:
                parts.append(f"bytes {pr['bytes_accessed']:.3g}")
            parts.append(f"collectives {pr.get('collective_count', 0)}"
                         f" ({pr.get('collective_bytes', 0)} B)")
            rows.append((f"perf {pr.get('name', '?')}", "  ".join(parts)))
            for c in pr.get("collectives", []):
                detail = (f"{c.get('kind')} {c.get('dtype')} "
                          f"x{c.get('ops')}  {c.get('bytes')} B")
                if c.get("group_size"):
                    detail += f"  group {c['group_size']}"
                rows.append(("  collective", detail))
        for r in perf.get("regressions", []):
            rows.append(("PERF REGRESSION",
                         f"{r.get('metric')}: observed {r.get('observed')}"
                         f" vs baseline {r.get('baseline')} "
                         f"(threshold {r.get('threshold')}, "
                         f"{r.get('direction', '?')} is better)"))
    # goodput attribution (obs/goodput.py): where every wall-clock
    # second went — the "where did the time go" table, with the
    # accounting-leak flag when buckets fail to cover the wall clock
    goodput = summary.get("goodput")
    if goodput:
        head = (f"{goodput['goodput_frac'] * 100:.1f}% productive over "
                f"{goodput['wall_s']:.1f} s wall")
        if goodput.get("source") == "intervals":
            head += "  (no terminal summary; accumulated from intervals)"
        if goodput.get("imbalance_frac", 0.0) > 0.02:
            head += (f"  ACCOUNTING LEAK "
                     f"{goodput['imbalance_frac'] * 100:.1f}%")
        rows.append(("goodput", head))
        wall = goodput.get("wall_s") or 0.0
        for name, secs in sorted(goodput.get("buckets", {}).items(),
                                 key=lambda kv: -kv[1]):
            if secs <= 0:
                continue
            pct = f" ({secs / wall * 100:.1f}%)" if wall > 0 else ""
            rows.append((f"  {name}", f"{secs:.2f} s{pct}"))
    # burn-rate alert timeline (obs/alerts.py): each fired episode with
    # its resolution — the pager history, replayable offline from the
    # same journal the live engine consumed
    alerts = summary.get("alerts")
    if alerts:
        head = f"{len(alerts['episodes'])} episode(s)"
        if alerts.get("still_firing"):
            head += f", {alerts['still_firing']} STILL FIRING"
        rows.append(("alerts", head))
        for a in alerts["episodes"]:
            detail = f"[{a.get('severity', '?')}]"
            if isinstance(a.get("value"), (int, float)) and \
                    isinstance(a.get("threshold"), (int, float)):
                detail += (f" value {a['value']:.4g} > "
                           f"threshold {a['threshold']:.4g}")
            if "resolved_ts" in a:
                detail += (f", resolved after {a.get('dur_s', 0.0):.1f} s"
                           if isinstance(a.get("dur_s"), (int, float))
                           else ", resolved")
            else:
                detail += ", still firing at journal end"
            rows.append((f"  {a.get('rule', '?')}", detail))
    # profiler captures: every decision the autoprof policy made, so the
    # table answers "why does this run have three trace dirs" directly
    for e in summary.get("captures", []):
        detail = f"step {e.get('step', '?')}"
        if e.get("z") is not None:
            detail += f" z={e['z']}"
        if e.get("outcome") in ("captured", "started") and e.get("dir"):
            detail += f" -> {e['dir']}"
        rows.append((f"capture {e.get('reason', '?')}",
                     f"{e.get('outcome', '?')} ({detail})"))
    for e in summary.get("flight_dumps", []):
        rows.append((f"flight {e.get('reason', '?')}",
                     f"{e.get('outcome', '?')} -> {e.get('dir', '?')}"))
    # lock health (obs/locksmith.py events): the one-line answer to "did
    # the serving plane's locking behave" — order violations are bugs,
    # contention rows are the tuning signal (which lock, how long)
    violations = summary.get("lock_violations", [])
    contention = summary.get("lock_contention", [])
    if violations or contention:
        by_lock: Dict[str, List[float]] = {}
        for e in contention:
            if isinstance(e.get("ms"), (int, float)):
                by_lock.setdefault(str(e.get("lock", "?")), []).append(
                    float(e["ms"]))
        parts = f"{len(violations)} order violation(s)"
        if by_lock:
            top = max(by_lock.items(), key=lambda kv: len(kv[1]))
            holds = [float(e["ms"]) for e in contention
                     if e.get("kind") == "hold"
                     and isinstance(e.get("ms"), (int, float))]
            parts += (f"; top contended {top[0]} ({len(top[1])}x, "
                      f"worst {max(top[1]):.1f} ms)")
            if holds:
                parts += f"; max hold {max(holds):.1f} ms"
        rows.append(("lock health", parts))
        for e in violations[:4]:
            rows.append(("  inversion",
                         f"{e.get('lock_a')} -> {e.get('lock_b')} on "
                         f"{e.get('thread', '?')} (reverse order seen on "
                         f"{e.get('prior_thread', '?')})"))
        if len(violations) > 4:
            rows.append(("  ...", f"{len(violations) - 4} more inversions"))
    # health findings: one row per event, aggregated counts first so a
    # 10k-spike run stays readable (only the first few render verbatim)
    health = summary.get("health", [])
    if health:
        by_kind: Dict[str, int] = {}
        for e in health:
            by_kind[e.get("kind", "?")] = by_kind.get(e.get("kind", "?"), 0) + 1
        rows.append(("health", " ".join(
            f"{k}x{n}" for k, n in sorted(by_kind.items()))))
        for e in health[:8]:
            kind = e.get("kind", "?")
            where = (f"step {e['step']}" if "step" in e
                     else f"epoch {e['epoch']}" if "epoch" in e else "")
            detail = ""
            if kind == "non_finite":
                detail = "fields=" + ",".join(e.get("fields", []))
            elif kind in ("loss_spike", "divergence"):
                detail = (f"loss={e.get('loss', 0):.4g} "
                          f"z={e.get('z', 0):.1f} "
                          f"streak={e.get('streak', '?')}")
            elif kind == "hang":
                detail = (f"stalled {e.get('stalled_s', '?')}s "
                          f"(deadline {e.get('timeout_s', '?')}s), "
                          f"{len(e.get('stacks', {}))} thread stacks dumped")
            rows.append((f"  {kind}", f"{where} {detail}".strip()))
        if len(health) > 8:
            rows.append(("  ...", f"{len(health) - 8} more health events"))
    width = max(len(k) for k, _ in rows)
    lines = ["=" * (width + 46)]
    lines += [f"{k:<{width}}  {v}" for k, v in rows]
    lines.append("=" * (width + 46))
    return "\n".join(lines)


def summarize_trace(path: str) -> List[dict]:
    """Per-span-name aggregate over a Chrome trace (obs/trace.py output):
    count, total/mean/p50/p95/max duration ms, sorted by total descending.
    The tail quantiles are what make a capture window or a straggler gap
    quantifiable from the CLI — a mean hides exactly the steps that
    triggered the capture."""
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    durs: Dict[str, List[float]] = {}
    for e in events:
        if e.get("ph") != "X":
            continue  # metadata / instant events carry no duration
        durs.setdefault(e.get("name", "?"), []).append(
            float(e.get("dur", 0.0)) / 1e3)
    out = []
    for name, ds in durs.items():
        out.append({
            "name": name,
            "count": len(ds),
            "total_ms": sum(ds),
            "mean_ms": sum(ds) / len(ds),
            "p50_ms": _percentile(ds, 0.5),
            "p95_ms": _percentile(ds, 0.95),
            "max_ms": max(ds),
        })
    return sorted(out, key=lambda a: -a["total_ms"])


def render_trace(spans: List[dict], path: str) -> str:
    if not spans:
        return f"trace {path}: no complete spans"
    w = max(len(s["name"]) for s in spans)
    lines = [f"-- span time summary: {path} --",
             f"{'span':<{w}}  {'count':>6}  {'total ms':>10}  "
             f"{'mean ms':>9}  {'p50 ms':>9}  {'p95 ms':>9}  {'max ms':>9}"]
    for s in spans:
        lines.append(f"{s['name']:<{w}}  {s['count']:>6}  "
                     f"{s['total_ms']:>10.1f}  {s['mean_ms']:>9.2f}  "
                     f"{s['p50_ms']:>9.2f}  {s['p95_ms']:>9.2f}  "
                     f"{s['max_ms']:>9.1f}")
    return "\n".join(lines)


_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(vals: List[float]) -> str:
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK[0] * len(vals)
    return "".join(_SPARK[int((v - lo) / (hi - lo) * (len(_SPARK) - 1))]
                   for v in vals)


def render_ledger(path: str, *, window: int = 16) -> str:
    """The perf-trajectory table over a tools/perf_gate.py ledger: one
    row per (metric, env fingerprint) with the last value, a sparkline
    of the last `window` runs, and the most recent gate verdict — the
    "is this metric drifting" answer without opening the JSONL."""
    from tools.perf_gate import PerfLedger

    rows = PerfLedger(path).read()
    if not rows:
        return f"perf ledger {path}: empty"
    series: Dict[tuple, List[dict]] = {}
    for r in rows:
        if isinstance(r.get("value"), (int, float)):
            series.setdefault(
                (str(r.get("metric", "?")), str(r.get("env_key", ""))),
                []).append(r)
    lines = [f"-- perf trajectory: {path} ({len(rows)} runs, "
             f"{len(series)} series) --"]
    w = max(len(m) for m, _ in series) if series else 6
    for (metric, _key), rs in sorted(series.items()):
        tail = rs[-int(window):]
        vals = [float(r["value"]) for r in tail]
        last = tail[-1]
        unit = last.get("unit") or ""
        verdict = last.get("verdict", "?")
        line = (f"{metric:<{w}}  {_sparkline(vals)}  "
                f"last {vals[-1]:.4g}{(' ' + unit) if unit else ''}  "
                f"[{verdict}]  (n={len(rs)})")
        lines.append(line)
    return "\n".join(lines)


def render_merged(events: List[dict]) -> str:
    """Render an obs_merge timeline: per-host step statistics side by
    side, then every detected straggler — the cross-host view a single
    journal cannot show."""
    hosts: Dict[int, List[dict]] = {}
    stragglers = []
    header = None
    for e in events:
        if e.get("event") == "note" and e.get("note") == "obs_merge":
            header = e
        elif e.get("event") == "straggler":
            stragglers.append(e)
        elif isinstance(e.get("host"), int):
            # integer hosts are obs_merge host INDICES; telemetry_server
            # events carry a bind address string in the same field and
            # belong to no host lane
            hosts.setdefault(int(e["host"]), []).append(e)
    lines = ["== merged multi-host timeline =="]
    if header:
        lines.append(f"hosts {header.get('hosts')}  "
                     f"sources {len(header.get('sources', []))}  "
                     f"stragglers {header.get('stragglers', 0)}")
    for h in sorted(hosts):
        evs = hosts[h]
        steps = [e for e in evs if e.get("event") == "step"]
        st = _stats([float(e["step_time_ms"]) for e in steps
                     if "step_time_ms" in e])
        terminal = next((e for e in reversed(evs)
                         if e.get("event") in ("exit", "crash")), None)
        status = ("no terminal event" if terminal is None
                  else terminal["event"])
        line = f"host {h}: {len(steps)} steps, {status}"
        if st:
            line += ("  step_time " + _fmt_stat(st, " ms"))
        lines.append(line)
    if stragglers:
        lines.append(f"-- stragglers ({len(stragglers)}) --")
        for e in stragglers[:16]:
            lines.append(
                f"step {e.get('step'):>6}  host {e.get('host')}  "
                f"gap {e.get('gap_ms'):.1f} ms  "
                f"(max {e.get('max_ms'):.1f} vs median "
                f"{e.get('median_ms'):.1f} over {e.get('hosts')} hosts)")
        if len(stragglers) > 16:
            lines.append(f"... {len(stragglers) - 16} more")
    else:
        lines.append("no stragglers detected")
    # cross-PROCESS request timelines (obs/merge.py trace_timelines):
    # one request's hops — stamped by obs/propagate.py trace context —
    # stitched across journals into a single causal sequence
    from deep_vision_tpu.obs.merge import trace_timelines

    timelines = trace_timelines(events)
    if timelines:
        lines.append(f"-- request timelines ({len(timelines)}) --")
        for tl in timelines[:8]:
            lines.append(
                f"trace {tl['trace_id']}  {len(tl['hops'])} hop(s), "
                f"{tl['spans']} span(s) across "
                f"{len(tl['processes'])} process(es)  "
                f"{tl['duration_ms']:.1f} ms")
            t0 = tl["hops"][0].get("ts") or 0.0
            for hop in tl["hops"][:12]:
                bits = [hop.get("event", "?")]
                for k in ("role", "service", "model", "outcome", "note"):
                    if hop.get(k) is not None:
                        bits.append(f"{k}={hop[k]}")
                if hop.get("run_id"):
                    bits.append(f"run {hop['run_id']}")
                dt = ((hop.get("ts") or t0) - t0) * 1e3
                lines.append(f"  +{dt:8.1f} ms  " + "  ".join(bits))
            if len(tl["hops"]) > 12:
                lines.append(f"  ... {len(tl['hops']) - 12} more hops")
        if len(timelines) > 8:
            lines.append(f"... {len(timelines) - 8} more traces")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("journals", nargs="+", help="journal JSONL path(s)")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="also render a per-span time summary of this "
                        "Chrome trace JSON (train.py --trace output)")
    p.add_argument("--merged", action="store_true",
                   help="the input is a tools/obs_merge.py merged "
                        "multi-host timeline: render per-host step "
                        "statistics and the detected stragglers")
    p.add_argument("--ledger", default=None, metavar="PATH",
                   help="also render the perf-trajectory table of this "
                        "tools/perf_gate.py ledger (sparkline per "
                        "metric, last gate verdict)")
    p.add_argument("--digest", default=None, metavar="PATH",
                   help="also render a step-time decomposition of this "
                        "profiler capture dir (tools/trace_digest.py)")
    args = p.parse_args(argv)

    if args.merged:
        events: List[dict] = []
        for path in args.journals:
            events.extend(read_journal(path))
        if not events:
            print("no events found", file=sys.stderr)
            return 1
        print(render_merged(events))
        _render_extras(args)
        return 0

    by_run: Dict[str, List[dict]] = {}
    for path in args.journals:
        for e in read_journal(path):
            by_run.setdefault(e.get("run_id", path), []).append(e)
    if not by_run:
        print("no events found", file=sys.stderr)
        return 1
    for run_id, events in by_run.items():
        print(render(summarize_run(events)))
    _render_extras(args)
    return 0


def _render_extras(args) -> None:
    if args.trace:
        print(render_trace(summarize_trace(args.trace), args.trace))
    if args.ledger:
        print(render_ledger(args.ledger))
    if args.digest:
        from tools.trace_digest import digest, render_digest

        print(render_digest(digest(args.digest)))


if __name__ == "__main__":
    raise SystemExit(main())
