"""Unit tests for vision ops: IoU, boxes, NMS, anchors, heatmaps.

Exact closed-form cases per SURVEY.md §4's test plan (the reference had none).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.ops import (
    YOLO_ANCHORS,
    YOLO_ANCHOR_MASKS,
    assign_anchors_to_grid,
    broadcast_iou,
    decode_yolo_boxes,
    encode_yolo_boxes,
    gaussian_heatmaps,
    non_maximum_suppression,
    xywh_to_xyxy,
    xyxy_to_xywh,
)
from deep_vision_tpu.ops.heatmaps import centernet_class_heatmap, gaussian_radius


def test_box_conversion_roundtrip():
    boxes = jnp.array([[0.5, 0.5, 0.2, 0.4], [0.1, 0.9, 0.05, 0.1]])
    assert jnp.allclose(xyxy_to_xywh(xywh_to_xyxy(boxes)), boxes, atol=1e-6)
    xyxy = xywh_to_xyxy(boxes)
    assert jnp.allclose(xyxy[0], jnp.array([0.4, 0.3, 0.6, 0.7]), atol=1e-6)


def test_broadcast_iou_exact():
    a = jnp.array([[0.0, 0.0, 1.0, 1.0], [0.0, 0.0, 0.5, 0.5]])
    b = jnp.array([[0.0, 0.0, 1.0, 1.0], [0.5, 0.5, 1.0, 1.0], [2.0, 2.0, 3.0, 3.0]])
    iou = broadcast_iou(a, b)
    assert iou.shape == (2, 3)
    assert iou[0, 0] == pytest.approx(1.0)
    assert iou[0, 1] == pytest.approx(0.25)
    assert iou[0, 2] == pytest.approx(0.0)
    assert iou[1, 1] == pytest.approx(0.0)  # touching, zero overlap


def test_yolo_box_decode_encode_roundtrip():
    anchors = jnp.asarray(YOLO_ANCHORS[6:9])
    g = 13
    raw = jax.random.normal(jax.random.PRNGKey(0), (2, g, g, 3, 9)) * 0.5
    boxes, obj, probs = decode_yolo_boxes(raw, anchors)
    assert boxes.shape == (2, g, g, 3, 4)
    xywh = xyxy_to_xywh(boxes)
    t = encode_yolo_boxes(xywh, anchors, g)
    # t_wh must invert exactly; t_xy matches sigmoid(raw_xy)
    assert jnp.allclose(t[..., 2:4], raw[..., 2:4], atol=1e-4)
    assert jnp.allclose(t[..., 0:2], jax.nn.sigmoid(raw[..., 0:2]), atol=1e-4)


def test_nms_suppresses_overlaps_keeps_distinct():
    boxes = jnp.array([[[0.1, 0.1, 0.4, 0.4],
                        [0.12, 0.12, 0.42, 0.42],   # overlaps box 0
                        [0.6, 0.6, 0.9, 0.9],       # distinct
                        [0.0, 0.0, 0.0, 0.0]]])     # padding
    scores = jnp.array([[0.9, 0.8, 0.7, 0.0]])
    out_b, out_s, out_c, valid = non_maximum_suppression(
        boxes, scores, max_detections=4, iou_threshold=0.5, score_threshold=0.1
    )
    assert int(valid[0]) == 2
    assert out_s[0, 0] == pytest.approx(0.9)
    assert out_s[0, 1] == pytest.approx(0.7)
    assert jnp.allclose(out_b[0, 0], boxes[0, 0])
    assert jnp.allclose(out_b[0, 1], boxes[0, 2])


def test_nms_multilabel_classes_dont_suppress_each_other():
    boxes = jnp.tile(jnp.array([[[0.1, 0.1, 0.4, 0.4]]]), (1, 2, 1))
    scores = jnp.array([[0.9, 0.8]])
    classes = jnp.array([[0, 1]])  # same box, two classes
    _, out_s, out_c, valid = non_maximum_suppression(
        boxes, scores, classes, max_detections=4, iou_threshold=0.5,
        score_threshold=0.1,
    )
    assert int(valid[0]) == 2
    assert set(np.asarray(out_c[0, :2]).tolist()) == {0, 1}


def test_anchor_assignment_places_box_in_right_cell():
    # one large box -> best anchor is in scale 0 (stride 32, anchors 6-8)
    boxes = jnp.array([[0.5, 0.5, 0.4, 0.35], [0.0, 0.0, 0.0, 0.0]])
    classes = jnp.array([3, 0])
    targets = assign_anchors_to_grid(
        boxes, classes, grid_sizes=(13, 26, 52), num_classes=5
    )
    assert [t.shape for t in targets] == [
        (13, 13, 3, 10), (26, 26, 3, 10), (52, 52, 3, 10)
    ]
    # box center 0.5*13 = 6.5 -> cell (6, 6)
    cell = targets[0][6, 6]  # (3, 10)
    slot = int(jnp.argmax(cell[:, 4]))
    assert cell[slot, 4] == 1.0  # objectness
    assert jnp.allclose(cell[slot, 0:4], boxes[0])
    assert cell[slot, 5 + 3] == 1.0  # one-hot class
    # nothing else anywhere: total objectness == 1
    assert sum(float(jnp.sum(t[..., 4])) for t in targets) == 1.0


def test_anchor_assignment_batch_via_vmap():
    boxes = jnp.zeros((4, 10, 4))
    classes = jnp.zeros((4, 10), jnp.int32)
    fn = jax.vmap(
        lambda b, c: assign_anchors_to_grid(b, c, (13,), num_classes=5)[0]
    )
    out = fn(boxes, classes)
    assert out.shape == (4, 13, 13, 3, 10)
    assert float(jnp.sum(out)) == 0.0  # all padding -> empty grids


def test_gaussian_heatmap_peak_and_visibility():
    pts = jnp.array([[10.0, 5.0], [-1.0, -1.0]])
    hm = gaussian_heatmaps(pts, 16, 32, sigma=1.0, visible=jnp.array([1, 1]))
    assert hm.shape == (16, 32, 2)
    assert hm[5, 10, 0] == pytest.approx(1.0)  # peak at (y=5, x=10)
    assert hm[5, 11, 0] == pytest.approx(np.exp(-0.5), abs=1e-5)
    assert float(jnp.sum(hm[..., 1])) == 0.0  # invisible point -> zeros


def test_centernet_heatmap_max_over_objects():
    centers = jnp.array([[4.0, 4.0], [4.0, 4.0], [0.0, 0.0]])
    classes = jnp.array([2, 2, 0])
    wh = jnp.array([[3.0, 3.0], [6.0, 6.0], [0.0, 0.0]])  # third is padding
    hm = centernet_class_heatmap(centers, classes, wh, 16, 16, num_classes=3)
    assert hm.shape == (16, 16, 3)
    assert hm[4, 4, 2] == pytest.approx(1.0)
    assert float(jnp.sum(hm[..., 0])) == 0.0  # padded object contributes nothing


def test_gaussian_radius_monotone_in_box_size():
    r_small = float(gaussian_radius(jnp.array([4.0, 4.0])))
    r_big = float(gaussian_radius(jnp.array([40.0, 40.0])))
    assert 0 < r_small < r_big
