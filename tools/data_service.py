"""Run a standalone shared dataset service (data/service.py) from the CLI.

    PYTHONPATH=. python tools/data_service.py --pattern 'shards/train-*' \
        --schema imagenet --batch-size 64 [--port 5757] [--workers 4] \
        [--host-id 0 --num-hosts 4] [--journal svc.jsonl]

Serves pre-decoded, pre-collated, fixed-shape batches over a local
socket until SIGTERM/SIGINT, at which point it drains cleanly (typed
`data_service` summary event in the journal). Trainers and evals attach
with `train.py --data-service HOST:PORT` or
`data.service.DataServiceClient`.

`--host-id/--num-hosts` apply `shard_for_host` so a multi-host fleet
runs one service per host over a disjoint, covering shard slice (the
per-host sharded input feed for parallel/multihost.py).

Prints `ready ADDRESS` on stdout once the socket is bound — the line a
launcher (or the data smoke) waits for.
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--pattern", required=True,
                   help="record shard glob (records.expand_shards)")
    p.add_argument("--schema", default="imagenet",
                   help="Example schema name (data/datasets.py SCHEMAS)")
    p.add_argument("--batch-size", type=int, required=True)
    p.add_argument("--resize", type=int, default=None, metavar="SIZE",
                   help="resize every sample to SIZExSIZE and scale to "
                        "float32 [0,1] before collating — REQUIRED for "
                        "variable-size schemas (imagenet JPEGs): batches "
                        "must be fixed-shape to collate and to keep "
                        "consumers at one compiled executable. Richer "
                        "augmentation chains belong to in-process "
                        "DataService construction (data/service.py)")
    p.add_argument("--workers", type=int, default=2,
                   help="decode worker processes")
    p.add_argument("--shuffle-buffer", type=int, default=512)
    p.add_argument("--no-shuffle", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--queue-depth", type=int, default=16,
                   help="encoded batches buffered ahead of the clients")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 binds an ephemeral port (printed on stdout)")
    p.add_argument("--host-id", type=int, default=0,
                   help="this host's index for per-host shard assignment")
    p.add_argument("--num-hosts", type=int, default=1)
    p.add_argument("--name", default="default")
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="typed data_worker_lost/recovered + data_service "
                        "events (tools/check_journal.py --strict validates)")
    p.add_argument("--worker-restarts", type=int, default=2)
    p.add_argument("--telemetry-port", type=int, default=None, metavar="PORT",
                   help="serve live /metrics /healthz /statusz over HTTP "
                        "(0 = auto-assign; discovery file lands next to the "
                        "journal, or the cwd without one). DVT_TELEMETRY=PORT "
                        "is the env equivalent (obs/telemetry.py)")
    args = p.parse_args(argv)

    from deep_vision_tpu.data.datasets import RecordDataset
    from deep_vision_tpu.data.service import DataService, shard_for_host

    shard_index, num_shards = shard_for_host(args.host_id, args.num_hosts)
    dataset = RecordDataset(
        args.pattern, args.schema, shuffle_shards=not args.no_shuffle,
        seed=args.seed, shard_index=shard_index, num_shards=num_shards,
    )
    transform = None
    if args.resize:
        from deep_vision_tpu.data import transforms as T
        from deep_vision_tpu.data.pipeline import Compose

        transform = Compose([T.Resize(args.resize), T.ToFloat()])
    journal = None
    if args.journal:
        from deep_vision_tpu.obs import RunJournal

        journal = RunJournal(args.journal, kind="data_service")
        journal.manifest(service=args.name, pattern=args.pattern,
                         host_id=args.host_id, num_hosts=args.num_hosts)
    svc = DataService(
        dataset, batch_size=args.batch_size, transform=transform,
        num_workers=args.workers,
        shuffle=not args.no_shuffle, shuffle_buffer=args.shuffle_buffer,
        seed=args.seed, queue_depth=args.queue_depth, host=args.host,
        port=args.port, name=args.name, journal=journal,
        worker_restarts=args.worker_restarts,
    ).start()
    print(f"ready {svc.address}", flush=True)

    tele_port = args.telemetry_port
    if tele_port is None:
        from deep_vision_tpu.core import knobs

        try:
            tele_port = knobs.get_int("DVT_TELEMETRY")
        except knobs.KnobError as e:
            print(f"warning: {e}; telemetry disabled", file=sys.stderr)
    telemetry = None
    if tele_port is not None:
        from deep_vision_tpu.obs.registry import get_registry
        from deep_vision_tpu.obs.telemetry import TelemetryServer

        disc_dir = (os.path.dirname(os.path.abspath(args.journal))
                    if args.journal else os.getcwd())
        telemetry = TelemetryServer(
            port=tele_port, role="data_service", registry=get_registry(),
            journal=journal, discovery_dir=disc_dir)
        try:
            telemetry.start()
        except OSError as e:
            print(f"warning: telemetry server failed to bind port "
                  f"{tele_port} ({e}); continuing without live endpoints",
                  file=sys.stderr)
            telemetry = None
        else:
            telemetry.add_health("data_service", svc.healthz)
            telemetry.add_status("data_service", svc.telemetry_status)
            print(f"telemetry http://{telemetry.address}/statusz",
                  flush=True)

    stop = threading.Event()

    def _on_signal(signum, frame):
        stop.set()  # flag only; teardown runs outside signal context

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    stop.wait()
    print("data_service: draining", flush=True)
    if telemetry is not None:
        telemetry.close()  # stop answering scrapes before draining state
    svc.close()
    if journal is not None:
        journal.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
