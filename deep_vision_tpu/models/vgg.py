"""VGG-16/19 (Simonyan & Zisserman 2014), configs D and E.

Parity targets: VGG/pytorch/models/vgg16.py:25-40 and vgg19.py (plain 3x3
stacks + maxpool, three FC-4096/4096/1000 head, dropout 0.5). The reference
trains without BN (per the paper); we keep that for parity and expose
`use_bn` for the modern variant.
"""
from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn

from deep_vision_tpu.models import register_model
from deep_vision_tpu.nn.layers import ConvBN

_CFG_D: Tuple[Tuple[int, int], ...] = ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512))
_CFG_E: Tuple[Tuple[int, int], ...] = ((2, 64), (2, 128), (4, 256), (4, 512), (4, 512))


class VGG(nn.Module):
    cfg: Tuple[Tuple[int, int], ...]
    num_classes: int = 1000
    dropout: float = 0.5
    use_bn: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        for n_convs, ch in self.cfg:
            for _ in range(n_convs):
                x = ConvBN(ch, (3, 3), use_bn=self.use_bn, use_bias=True)(x, train)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096)(x))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096)(x))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


@register_model("vgg16")
def vgg16(num_classes: int = 1000, **kw):
    return VGG(cfg=_CFG_D, num_classes=num_classes, **kw)


@register_model("vgg19")
def vgg19(num_classes: int = 1000, **kw):
    return VGG(cfg=_CFG_E, num_classes=num_classes, **kw)
