from deep_vision_tpu.parallel.mesh import (
    MeshSpec,
    create_mesh,
    data_sharding,
    replicated,
    shard_batch,
    local_mesh_devices,
)
