"""Backend registry: the ONE module allowed to compare platform strings.

ROADMAP item 4 (multi-backend PJRT seam), first concrete step. Before
this module, "platform" was an implicit axis enforced by convention:
eight call sites across ops/pallas/, ops/nms.py, parallel/ and models/
each hand-rolled `jax.default_backend() == "tpu"` to decide whether a
Pallas kernel compiles natively or must run interpreted, and which NMS
selection backend is the default. The DV201 lint rule
(lint/distlint.py) now fails any such comparison OUTSIDE this module;
routing decisions read a `BackendProfile` instead, so adding a new
PJRT platform (or re-tuning what 'gpu' means once Mosaic-GPU lands) is
one table row here, not a grep across the tree.

Deliberately NOT wrapped: telemetry/fingerprint call sites that only
RECORD the platform string (obs/journal.py run manifests, excache
fingerprints, preflight detail lines) — recording is not routing, and
DV201 only fires on comparisons.

jax is imported lazily so stdlib-only consumers (lint, tools) can
import the module without paying the jax tax.
"""
from __future__ import annotations

import dataclasses
from typing import Dict

__all__ = [
    "BackendProfile",
    "BACKENDS",
    "current_platform",
    "get_backend",
    "is_tpu",
    "pallas_interpret",
    "default_nms_impl",
]


@dataclasses.dataclass(frozen=True)
class BackendProfile:
    """What the stack needs to know about one PJRT platform to route
    work — capabilities, not a platform name to compare against."""

    name: str
    #: Mosaic compiles Pallas kernels natively; elsewhere they run
    #: under `interpret=True` (the CPU test path).
    pallas_compiled: bool
    #: default NMS selection backend (ops/nms.py `impl='auto'`).
    nms_impl: str


BACKENDS: Dict[str, BackendProfile] = {
    "tpu": BackendProfile(name="tpu", pallas_compiled=True,
                          nms_impl="pallas"),
    "cpu": BackendProfile(name="cpu", pallas_compiled=False,
                          nms_impl="lax"),
    "gpu": BackendProfile(name="gpu", pallas_compiled=False,
                          nms_impl="lax"),
}

#: any platform without a curated row (plugin PJRT backends) routes
#: like CPU: interpret Pallas, lax NMS — slow beats wrong.
_FALLBACK = BACKENDS["cpu"]


def current_platform() -> str:
    """The active PJRT platform name (`jax.default_backend()`)."""
    import jax

    return jax.default_backend()


def get_backend() -> BackendProfile:
    return BACKENDS.get(current_platform(), _FALLBACK)


def is_tpu() -> bool:
    return current_platform() == "tpu"


def pallas_interpret() -> bool:
    """Should Pallas kernels run under `interpret=True`? The default
    for every `interpret=None` kernel entry point."""
    return not get_backend().pallas_compiled


def default_nms_impl() -> str:
    return get_backend().nms_impl
