"""Pipeline parallelism: GPipe-style microbatch streaming over a mesh axis.

The reference has no pipeline parallelism anywhere (its only distribution is
single-host data parallel, SURVEY.md §2.5); this module is part of the
framework's first-class distributed story (DP x TP x PP x SP x EP). Design is
the TPU-native schedule: stages live on consecutive devices of a named mesh
axis, activations hop stage-to-stage with a single `ppermute` per tick (one
ICI hop — neighbours on the axis are physical ICI neighbours on a TPU
torus), and the whole (stages + microbatches - 1)-tick schedule is a
`lax.scan` under `shard_map`, so XLA sees one fused SPMD program and the
GPipe backward schedule falls out of reverse-mode AD over the scan — no
hand-written 1F1B state machine.

Contract: every stage maps activations of one fixed shape to the same shape
(pick stage boundaries accordingly — e.g. hourglass stacks, or the uniform
trunk of a deep residual network; put shape-changing stems/heads outside the
pipelined trunk). Per-stage params are stacked on a leading `num_stages`
axis and sharded over the pipeline axis, so each device holds exactly its
stage's weights: model memory scales 1/S with pipeline depth.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deep_vision_tpu.parallel.mesh import MODEL_AXIS


def stack_pipeline_params(params_list):
    """Stack S per-stage param pytrees on a new leading stage axis.

    All stages must share one tree structure and per-leaf shapes (the
    fixed-activation-shape contract above implies this for conv/dense
    trunks).
    """
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_param_sharding(mesh: Mesh, stacked_params,
                            axis_name: str = MODEL_AXIS):
    """Shard the leading (stage) axis of stacked params over `axis_name`."""
    def rule(p):
        return NamedSharding(mesh, P(axis_name, *([None] * (p.ndim - 1))))

    return jax.tree_util.tree_map(rule, stacked_params)


def _pipeline_local(stacked_params, x, *, stage_fn, axis_name: str,
                    n_micro: int):
    """Per-device body (under shard_map).

    stacked_params: this device's (1, ...) slice of the stage-stacked tree.
    x: the full (B, ...) input (replicated; stage 0 reads it).
    """
    params = jax.tree_util.tree_map(lambda p: p[0], stacked_params)
    s = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    assert x.shape[0] % n_micro == 0, (
        f"batch {x.shape[0]} not divisible into {n_micro} microbatches"
    )
    micro = x.reshape(n_micro, x.shape[0] // n_micro, *x.shape[1:])
    fwd = [(i, i + 1) for i in range(s - 1)]  # stage i -> i+1 (no wraparound)

    def tick(carry, t):
        act, out = carry
        # stage 0 injects microbatch t (clipped: ticks past the last
        # injection feed a dummy that drains off the end unrecorded)
        inject = micro[jnp.clip(t, 0, n_micro - 1)]
        cur = jnp.where(my == 0, inject, act)
        y = stage_fn(params, cur)
        # the last stage's tick-t output is microbatch t-(s-1); the window
        # check masks both the fill bubble (idx < 0) and the drain dummies
        idx = t - (s - 1)
        record = (my == s - 1) & (idx >= 0) & (idx < n_micro)
        out = jnp.where(
            record,
            jax.lax.dynamic_update_index_in_dim(
                out, y, jnp.clip(idx, 0, n_micro - 1), 0
            ),
            out,
        )
        act = jax.lax.ppermute(y, axis_name, fwd)
        return (act, out), None

    act0 = jnp.zeros_like(micro[0])
    out0 = jnp.zeros_like(micro)
    act0 = jax.lax.pvary(act0, (axis_name,))
    out0 = jax.lax.pvary(out0, (axis_name,))
    (_, out), _ = jax.lax.scan(
        tick, (act0, out0), jnp.arange(n_micro + s - 1)
    )
    # only the last stage holds real outputs (everyone else accumulated
    # zeros), so a psum over the axis is a broadcast of the result
    out = jax.lax.psum(out, axis_name)
    return out.reshape(x.shape)


def pipeline_apply(
    stage_fn: Callable,
    stacked_params,
    x,
    mesh: Mesh,
    *,
    num_microbatches: int,
    axis_name: str = MODEL_AXIS,
):
    """Run `x` through S pipelined stages sharded over `axis_name`.

    stage_fn: (stage_params, act) -> act, shape-preserving.
    stacked_params: pytree with leading stage axis == mesh.shape[axis_name]
    (see `stack_pipeline_params`); device i computes stage i.
    x: (B, ...) global batch, B divisible by num_microbatches.

    Differentiable end-to-end: grads w.r.t. stacked_params come back with
    the same stage-sharded layout (reverse ppermutes ride the same ICI
    hops), so a pipelined train step is just jax.grad over this call.
    """
    n_stages = mesh.shape[axis_name]
    lead = {p.shape[0] for p in jax.tree_util.tree_leaves(stacked_params)}
    if lead != {n_stages}:
        raise ValueError(
            f"stacked params lead dims {lead} != {n_stages} pipeline stages"
        )
    body = functools.partial(
        _pipeline_local,
        stage_fn=stage_fn,
        axis_name=axis_name,
        n_micro=num_microbatches,
    )
    param_specs = jax.tree_util.tree_map(
        lambda p: P(axis_name, *([None] * (p.ndim - 1))), stacked_params
    )
    mapped = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
    )
    return mapped(stacked_params, x)
