"""Regression guard for the r4 V-MoE "router stall" root cause.

`artifacts/vmoe_stall_analysis_r04.md` (hardware, vmoe_s16): training the
attention family with AdamW at full LR from step 0 produces a plateau at the
uniform-prediction loss (ln C) that a short linear warmup removes entirely —
the collapsed MoE router during the plateau is a symptom of the optimizer
transient, not an MoE defect, and the shipped `vit_s16`/`vmoe_s16` configs
carry `warmup_epochs: 5` as the validated mitigation.

This CPU-sized reproduction (tiny 2-block V-MoE, 16-class memorization
fixture, the same `_train_step`/`build_optimizer` path as
`tools/convergence_run.py --warmup`) encodes both curves' qualitative shape
so the finding can't silently rot: no-warmup still sits near ln C at step
30 while the warmed-up run has escaped, and the warmed-up run converges.
Seed pinned (seed 0, LR 6e-3, this platform's CPU backend): no-warmup@30
= 2.76 vs warmup@30 = 0.20 — the curves are separated by >10x at every
assertion's margin. At 3e-3 the transient no longer shows on current
jax/XLA (both runs escape by step 30: nowarm@30 = 0.67), so the LR is
pinned where the plateau reproduces deterministically, matching how the
hardware runs needed vmoe_s16 scale for it to show at 1e-3.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deep_vision_tpu.core.train_state import create_train_state
from deep_vision_tpu.models.vit import ViT
from deep_vision_tpu.tools.convergence_run import _train_step
from deep_vision_tpu.train.optimizers import build_optimizer

CLASSES = 16
LR = 6e-3  # at tiny scale the transient needs the larger LR to show; the
           # hardware runs reproduced it at vmoe_s16 scale with 1e-3


def _run(warmup: int, steps: int):
    rng = np.random.RandomState(0)
    batch = {
        "image": jnp.asarray(rng.rand(32, 32, 32, 3).astype(np.float32)),
        "label": jnp.asarray(np.arange(32) % CLASSES, jnp.int32),
    }
    model = ViT(depth=2, dim=64, num_heads=4, patch=8,
                num_classes=CLASSES, num_experts=4)
    sched = optax.linear_schedule(0.0, LR, warmup) if warmup else LR
    tx = build_optimizer("adamw", sched, weight_decay=1e-4)
    state = create_train_state(model, tx, jnp.ones((2, 32, 32, 3)),
                               jax.random.PRNGKey(0))
    step = jax.jit(functools.partial(_train_step, aux_weight=0.01),
                   donate_argnums=0)
    at30 = final = None
    for i in range(steps):
        state, metrics = step(state, batch)
        if i == 30:
            at30 = float(metrics["loss"])
        if i == steps - 1:
            final = float(metrics["loss"])
    return at30, final


def test_warmup_removes_the_no_warmup_plateau():
    uniform = float(np.log(CLASSES))  # 2.77: the stall's loss level
    nowarm_at30, _ = _run(warmup=0, steps=31)
    warm_at30, warm_final = _run(warmup=50, steps=80)
    # the plateau exists without warmup: still near the uniform loss
    assert nowarm_at30 > 0.6 * uniform, nowarm_at30
    # warmup escapes it: well below both the plateau and the no-warmup run
    assert warm_at30 < 1.2, warm_at30
    assert warm_at30 < 0.5 * nowarm_at30, (warm_at30, nowarm_at30)
    # and the warmed-up recipe actually converges
    assert warm_final < 0.1, warm_final
