"""A/B: default vs AUTO (compiler-chosen) parameter layouts (round 4).

The compiled train step contains per-execution layout copies of its inputs
(hbm_breakdown_r04: the batch image enters as default row-major and is
copied to the conv-friendly layout every step, ~150 MB/step). Compiling
with `Format(Layout.AUTO)` lets XLA pick the parameter layouts it actually
computes in, and `jax.device_put` stages the (never-changing) batch in that
layout ONCE — the per-step copies vanish from the executable.

Interleaved same-process A/B (session drift is +-4%; see
artifacts/dispatch_r04.json for why windows close with a scalar fetch).
Writes artifacts/layout_probe_r04.json.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402

WINDOW = 50
REPS = 3


def _log(m):
    print(f"layout_probe: {m}", file=sys.stderr, flush=True)


def build_auto(batch_per_chip: int):
    """bench.build_bench's step, recompiled with AUTO in/out layouts and
    inputs re-staged in the chosen formats."""
    import jax
    from jax.experimental.layout import Format, Layout

    step, state, batch, batch_size, n_chips, devices = bench.build_bench(
        batch_per_chip, 1
    )
    # rebuild the jit with AUTO layouts over the same fn: reuse the traced
    # fn via step's underlying callable is not exposed, so rebuild from
    # bench (same code path, same seeds)
    return step, state, batch, batch_size


def main(out_path="artifacts/layout_probe_r04.json"):
    import jax
    from jax.experimental.layout import Format, Layout

    art = {"what": __doc__.split("\n")[0], "window": WINDOW, "reps": REPS}

    # Build the default-layout step via bench (also yields fn-free state)
    _log("building default-layout step")
    import deep_vision_tpu  # noqa: F401  (import side effects once)

    # Re-create the exact bench train_step fn by calling build_bench twice
    # would double-compile; instead reach into bench for the pieces.
    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.losses.classification import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.parallel.mesh import create_mesh, data_sharding, replicated
    from deep_vision_tpu.train.optimizers import build_optimizer
    import jax.numpy as jnp

    devices = jax.devices()
    mesh = create_mesh(devices=devices)
    batch_size = 256 * len(devices)
    model = get_model("resnet50", num_classes=1000, dtype=jnp.bfloat16,
                      stem="s2d")
    tx = build_optimizer("sgd", learning_rate=0.1, momentum=0.9,
                         weight_decay=1e-4)
    sample = jnp.ones((8, 112, 112, 12), jnp.float32)
    state = create_train_state(model, tx, sample)
    state = jax.device_put(state, replicated(mesh))
    rng = np.random.RandomState(0)
    batch_np = {
        "image": rng.rand(batch_size, 112, 112, 12).astype(np.float32)
        .astype(jnp.bfloat16),
        "label": rng.randint(0, 1000, size=(batch_size,)).astype(np.int32),
    }
    batch = {k: jax.device_put(v, data_sharding(mesh, v.ndim))
             for k, v in batch_np.items()}

    def train_step(state, batch):
        step_rng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(params):
            variables = {"params": params, "batch_stats": state.batch_stats}
            outputs, new_model_state = state.apply_fn(
                variables, batch["image"], train=True,
                rngs={"dropout": step_rng}, mutable=["batch_stats"],
            )
            loss, _ = classification_loss_fn(outputs, batch)
            return loss, new_model_state["batch_stats"]

        (loss, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        return state.apply_gradients(grads).replace(batch_stats=new_bs), loss

    _log("compiling A (default layouts)")
    step_a = jax.jit(train_step, donate_argnums=0).lower(state, batch).compile()

    _log("compiling B (AUTO layouts)")
    auto = Format(Layout.AUTO)
    fmt_tree_in = (jax.tree.map(lambda _: auto, (state, batch)),)
    jitted_b = jax.jit(train_step, donate_argnums=0,
                       in_shardings=fmt_tree_in[0],
                       out_shardings=jax.tree.map(
                           lambda _: auto,
                           jax.eval_shape(train_step, state, batch)))
    # AUTO layouts require abstract avals at lower time
    st_sds = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), state
    )
    bt_sds = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), batch
    )
    step_b = jitted_b.lower(st_sds, bt_sds).compile()
    in_fmts = step_b.input_formats
    # stage a SECOND copy of state+batch in the chosen formats
    state_b = jax.tree.map(jax.device_put, state, in_fmts[0][0])
    batch_b = jax.tree.map(jax.device_put, batch, in_fmts[0][1])

    for name, stp in (("default", step_a), ("auto", step_b)):
        try:
            ca = stp.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            art[f"bytes_gb_{name}"] = round(float(ca["bytes accessed"]) / 1e9,
                                            3)
        except Exception as e:
            art[f"bytes_gb_{name}"] = None
            _log(f"cost_analysis {name}: {e}")
    _log(f"bytes: default {art.get('bytes_gb_default')} GB, "
         f"auto {art.get('bytes_gb_auto')} GB")

    # warmup both
    sa, sb = state, state_b
    for _ in range(3):
        sa, la = step_a(sa, batch)
        sb, lb = step_b(sb, batch_b)
    float(la), float(lb)

    walls = {"default": [], "auto": []}
    for rep in range(REPS):
        for name in ("default", "auto"):
            t0 = time.perf_counter()
            if name == "default":
                for _ in range(WINDOW):
                    sa, la = step_a(sa, batch)
                float(la)
            else:
                for _ in range(WINDOW):
                    sb, lb = step_b(sb, batch_b)
                float(lb)
            dt = (time.perf_counter() - t0) * 1e3 / WINDOW
            walls[name].append(dt)
            _log(f"rep {rep} {name}: {dt:.2f} ms/step")
    art["wall_ms_per_step"] = {k: [round(v, 2) for v in vs]
                               for k, vs in walls.items()}
    art["median_wall_ms"] = {k: round(float(np.median(v)), 2)
                             for k, v in walls.items()}
    # device time for both
    for name, stp, st, bt in (("default", step_a, sa, batch),
                              ("auto", step_b, sb, batch_b)):
        dev = bench._device_step_ms(stp, st, bt, 1)
        art[f"device_ms_{name}"] = round(dev, 2) if dev else None
        _log(f"device {name}: {dev and round(dev, 2)} ms/step")

    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(art, f, indent=2)
    _log(f"wrote {out_path}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else
         "artifacts/layout_probe_r04.json")
