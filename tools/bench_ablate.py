"""Ablation artifact for the README's perf claims (round 4).

Measures, on the real chip in ONE process with interleaved windows
(session drift is +-4%), the three design choices the README credits for
the ResNet-50 number, plus the flash-attention win:

- **s2d stem** (flagship): host lays out (H/2, W/2, 12); stem conv is
  math-identical to 7x7/s2 (tests/test_models_classifiers.py) but
  MXU-friendly — vs the plain conv7 stem on (H, W, 3).
- **fused single-pass BN** (nn/layers.py BatchNorm): activation never
  materialized in f32 — vs flax `nn.BatchNorm` (which promotes the full
  tensor to f32), swapped in by monkeypatching `FusedBatchNorm`.
- **flash vs dense attention**: the Pallas kernel vs the exact dense
  einsum (re-uses tools/bench_models.py bench_flash).

Writes artifacts/ablate_r04.json; every README perf claim should cite a
number from this file or artifacts/models_bench.json. Run solo on the chip.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402

WINDOW = 100  # window-closing fetch costs ~118 ms once per window; 100
              # steps caps the per-step bias at ~1.2 ms (was 50 in r4 —
              # fine for the ResNet ms/step scale, but the short flash
              # attention calls need the longer window; see bench_models)
REPS = 3
BATCH = 128  # flagship batch (artifacts/batch_scaling_r04.json)


def _log(m):
    print(f"ablate: {m}", file=sys.stderr, flush=True)


from contextlib import contextmanager


@contextmanager
def _swap_bn(unfused: bool):
    """Swap EVERY FusedBatchNorm the ResNet path sees for flax nn.BatchNorm.

    `from ... import FusedBatchNorm` binds the name into each model module,
    so patching only nn.layers would leave resnet.py's direct call sites
    (stem BN, bottleneck zero-init BN) fused — the r4 reviewer caught that.
    flax BatchNorm takes the same kwargs ConvBN/resnet pass and promotes
    the activation to f32 (the exact behavior the fused BN avoids).
    """
    import flax.linen as nn

    from deep_vision_tpu.models import resnet as R
    from deep_vision_tpu.nn import layers as L

    if not unfused:
        yield
        return
    saved = (L.FusedBatchNorm, R.FusedBatchNorm)
    L.FusedBatchNorm = nn.BatchNorm
    R.FusedBatchNorm = nn.BatchNorm
    try:
        yield
    finally:
        L.FusedBatchNorm, R.FusedBatchNorm = saved


def make_step(*, stem="s2d", unfused_bn=False):
    """The bench train step with the ablation knobs applied.

    bench.make_train_parts builds the exact flagship program (BATCH images
    PER CHIP, like bench.py); the BN swap stays active through construction
    AND the jit trace. All reported rates are per chip: XLA cost analysis
    is per-device under SPMD and BATCH/time is the per-chip rate."""
    import jax

    with _swap_bn(unfused_bn):
        train_step, state, batch, *_ = bench.make_train_parts(
            BATCH, stem=stem
        )
        step = jax.jit(train_step, donate_argnums=0).lower(
            state, batch
        ).compile()
    return step, state, batch


VARIANTS = [
    ("flagship_s2d_fused_bn", dict(stem="s2d", unfused_bn=False)),
    ("conv7_stem", dict(stem="conv7", unfused_bn=False)),
    ("unfused_flax_bn", dict(stem="s2d", unfused_bn=True)),
]


def main(out_path="artifacts/ablate_r04.json", skip_flash=False,
         journal_path=None):
    from deep_vision_tpu.obs import RunJournal

    journal = RunJournal(
        journal_path or os.path.splitext(out_path)[0] + ".journal.jsonl",
        kind="bench",
    )
    journal.manifest(config={"tool": "bench_ablate", "out": out_path,
                             "batch_per_chip": BATCH, "window": WINDOW,
                             "reps": REPS})
    art = {"what": __doc__.split("\n")[0], "batch_per_chip": BATCH,
           "window": WINDOW, "reps": REPS}
    built = {}
    for name, kw in VARIANTS:
        try:
            t0 = time.perf_counter()
            step, state, batch = make_step(**kw)
            row = {"variant": name,
                   "compile_s": round(time.perf_counter() - t0, 1)}
            try:
                ca = step.cost_analysis()
                ca = ca[0] if isinstance(ca, (list, tuple)) else ca
                row["bytes_gb_per_step"] = round(
                    float(ca["bytes accessed"]) / 1e9, 3
                )
                row["gflops_per_image"] = round(
                    float(ca["flops"]) / 1e9 / BATCH, 2
                )
            except Exception as e:
                _log(f"{name} cost_analysis: {e}")
            for _ in range(3):
                state, loss = step(state, batch)
            float(loss)
            built[name] = [step, state, batch, row, []]
            _log(f"{name}: compiled {row['compile_s']}s, "
                 f"{row.get('bytes_gb_per_step')} GB/step")
        except KeyboardInterrupt:
            raise
        except Exception as e:
            _log(f"{name} FAILED: {type(e).__name__}: {e}")
            built[name] = None
            art.setdefault("errors", []).append(
                f"{name}: {type(e).__name__}: {e}"
            )
    for rep in range(REPS):
        for name, slot in built.items():
            if slot is None or (isinstance(slot, tuple)
                                and slot[0] == "done"):
                continue
            step, state, batch, row, dts = slot
            try:
                t0 = time.perf_counter()
                for _ in range(WINDOW):
                    state, loss = step(state, batch)
                float(loss)
                dts.append((time.perf_counter() - t0) / WINDOW)
                slot[1] = state
                _log(f"rep {rep} {name}: {dts[-1] * 1e3:.2f} ms/step")
            except KeyboardInterrupt:
                raise
            except Exception as e:
                # donated state is gone: stop timing this variant, but KEEP
                # its row (partial reps + the error) in the artifact
                msg = f"rep {rep} {name}: {type(e).__name__}: {e}"
                _log(f"dropped: {msg}")
                row["error"] = msg
                art.setdefault("errors", []).append(msg)
                built[name] = ("done", row, dts)
    rows = []
    flagship = None
    for name, slot in built.items():
        if slot is None:
            continue
        if isinstance(slot, tuple) and slot[0] == "done":
            _, row, dts = slot
            if dts:
                wall = float(np.median(dts)) * 1e3
                row["wall_ms_per_step"] = round(wall, 2)
                row["wall_images_per_sec_per_chip"] = round(BATCH / wall * 1e3, 1)
            rows.append(row)
            continue
        step, state, batch, row, dts = slot
        if dts:
            wall = float(np.median(dts)) * 1e3
            row["wall_ms_per_step"] = round(wall, 2)
            row["wall_images_per_sec_per_chip"] = round(BATCH / wall * 1e3, 1)
        dev = bench._device_step_ms(step, state, batch, 1)
        if dev:
            row["device_ms_per_step"] = round(dev, 2)
            row["device_images_per_sec_per_chip"] = round(BATCH / dev * 1e3, 1)
        if name == "flagship_s2d_fused_bn":
            flagship = row
        rows.append(row)
    for row in rows:
        if flagship and row is not flagship and row.get("device_ms_per_step") \
                and flagship.get("device_ms_per_step"):
            row["slowdown_vs_flagship"] = round(
                row["device_ms_per_step"] / flagship["device_ms_per_step"], 3
            )
    art["resnet50_variants"] = rows
    for row in rows:
        journal.bench(row.get("variant", "?"), row)
    if not skip_flash:
        try:
            from tools.bench_models import bench_flash

            art["flash_attention"] = bench_flash()
            _log(f"flash: {art['flash_attention']}")
            journal.bench("flash_attention", art["flash_attention"])
        except Exception as e:
            art.setdefault("errors", []).append(
                f"flash: {type(e).__name__}: {e}"
            )
            _log(f"flash failed: {e}")
    for err in art.get("errors", []):
        journal.write("note", note=err)
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(art, f, indent=2)
    journal.close()
    _log(f"wrote {out_path}")


if __name__ == "__main__":
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--out", default="artifacts/ablate_r04.json")
    p.add_argument("--journal", default=None,
                   help="bench-journal JSONL (default: <out>.journal.jsonl)")
    p.add_argument("--skip-flash", action="store_true")
    a = p.parse_args()
    main(a.out, a.skip_flash, a.journal)
