"""Int8 post-training quantization for the serving path.

The second memory-bandwidth lever of speed arc 2 (the first is the
fused kernels): conv/dense kernels are stored int8 with per-output-
channel symmetric scales and dequantized INSIDE the jitted executable
(``q8.astype(f32) * scale`` feeding the matmul), so the serving engine
streams a quarter of the weight bytes from HBM while every accumulation
stays f32 and activations stay f16/f32 — weight-only PTQ, the
production-inference table stakes (SNIPPETS.md [2] shards torch.int8
weights as a matter of course).

The contract is calibrate -> gate -> swap:

1. :func:`quantize_variables` walks the weight tree and replaces each
   selected kernel leaf with ``{"q8": int8, "scale": f32(c_out,)}``;
   biases, norm scales, and batch stats stay f32 (they are tiny and
   precision-critical).
2. :func:`calibrate_and_quantize` runs the f32 reference and the
   quantized function over a representative batch stream and computes
   the accuracy delta — top-1 disagreement for logits-shaped outputs,
   relative output MSE otherwise. A delta above ``tolerance`` REFUSES
   to serve: typed ``quant_calibrated{model, delta, accepted: false}``
   + :class:`QuantizationRejected`, because an int8 engine that ships
   silently degraded predictions is worse than the f32 bandwidth bill.
3. The accepted ``QuantizedModel`` registers on an Engine like any
   other model (its variables ARE the int8 tree, its fn dequantizes
   in-jit), warms through the executable cache like any other pair, and
   subsequent re-calibrated int8 trees hot-swap through the existing
   ``Engine.set_variables`` / ``clone_with_variables`` machinery — the
   avals (int8 q8 + f32 scales) match, so the swap never compiles.

Scales ride checkpoints through the crc32c sidecar:
:func:`scales_host_state` / :func:`apply_scales` round-trip the
per-channel scales as JSON host state next to the int8 arrays.
"""
from __future__ import annotations

from typing import Callable, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deep_vision_tpu.serve.engine import ServeError

__all__ = [
    "QuantizationRejected",
    "QuantizedModel",
    "apply_scales",
    "calibrate_and_quantize",
    "dequantize_variables",
    "quantize_variables",
    "quantized_fn",
    "scales_host_state",
]

#: leaf names treated as matmul/conv kernels (flax's `kernel`, the
#: toy/test convention `w*`); everything else stays f32
KERNEL_NAMES = ("kernel", "w", "w1", "w2")

#: marker keys of one quantized leaf in the output tree
_Q_KEYS = frozenset(("q8", "scale"))


class QuantizationRejected(ServeError):
    """The int8 engine's accuracy delta exceeded the gate; serving the
    f32 engine is the only honest fallback."""


def _default_select(path: tuple, leaf) -> bool:
    dt = getattr(leaf, "dtype", None)
    return (bool(path) and path[-1] in KERNEL_NAMES
            and getattr(leaf, "ndim", 0) >= 2
            and dt is not None and jnp.issubdtype(dt, jnp.floating))


def _is_quantized_leaf(node) -> bool:
    return (isinstance(node, dict) and set(node) == _Q_KEYS
            and getattr(node["q8"], "dtype", None) == jnp.int8)


def quantize_variables(variables, select: Optional[Callable] = None):
    """(qvars, report): the weight tree with each selected kernel leaf
    replaced by ``{"q8": int8, "scale": f32}``.

    Per-OUTPUT-channel symmetric scales: the output channel is the last
    axis in both flax conventions (dense ``(d_in, d_out)``, conv
    ``(kh, kw, c_in, c_out)``), so ``scale = amax(|w|, all-but-last) /
    127`` and ``q8 = clip(round(w / scale), -127, 127)``. Symmetric
    (no zero point) keeps the in-jit dequant one multiply.
    """
    select = select or _default_select
    report = {"quantized_leaves": 0, "skipped_leaves": 0,
              "bytes_f32": 0, "bytes_int8": 0}

    def walk(node, path):
        if isinstance(node, dict):
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        if hasattr(node, "items"):  # FrozenDict and friends
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        if not select(path, node):
            report["skipped_leaves"] += 1
            return node
        w = np.asarray(node, np.float32)
        amax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)))
        scale = np.maximum(amax / 127.0, 1e-12).astype(np.float32)
        q8 = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
        report["quantized_leaves"] += 1
        report["bytes_f32"] += w.nbytes
        report["bytes_int8"] += q8.nbytes + scale.nbytes
        return {"q8": q8, "scale": scale}

    qvars = walk(variables, ())
    if report["quantized_leaves"] == 0:
        raise ServeError(
            "quantize_variables found no kernel leaves (names "
            f"{KERNEL_NAMES}, ndim >= 2); pass select= for exotic trees")
    report["compression"] = round(
        report["bytes_f32"] / max(1, report["bytes_int8"]), 2)
    return qvars, report


def dequantize_variables(qvars):
    """The f32 weight tree, computed INSIDE jit: ``q8.astype(f32) *
    scale`` per quantized leaf (broadcast over the output channel).
    Accumulation downstream is f32 because the dequantized operand is."""
    def walk(node):
        if _is_quantized_leaf(node):
            return node["q8"].astype(jnp.float32) * node["scale"]
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if hasattr(node, "items"):
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(qvars)


def quantized_fn(fn: Callable) -> Callable:
    """Wrap a serving predict fn ``fn(variables, images)`` so it takes
    the int8 tree: dequant happens in-trace, so XLA fuses the
    ``int8 -> f32 * scale`` expansion into the consumer and the weight
    bytes crossing HBM are the int8 ones."""
    def qfn(qvariables, images):
        return fn(dequantize_variables(qvariables), images)

    return qfn


class QuantizedModel:
    """An accepted calibrate-and-quantize result, ready to register:
    ``engine.register(m.name, m.fn, m.variables, ...)``."""

    __slots__ = ("name", "fn", "variables", "report", "delta", "metric",
                 "tolerance")

    def __init__(self, name, fn, variables, report, delta, metric,
                 tolerance):
        self.name = name
        self.fn = fn
        self.variables = variables
        self.report = report
        self.delta = delta
        self.metric = metric
        self.tolerance = tolerance


def _accuracy_delta(f32_outs: list, q_outs: list) -> tuple:
    """(delta, metric): top-1 disagreement when the output is a single
    logits-shaped array, relative output MSE otherwise (both in [0, ~1],
    0 = identical)."""
    first = f32_outs[0]
    logits_shaped = (not isinstance(first, dict)
                     and getattr(first, "ndim", 0) == 2)
    if logits_shaped:
        mismatch = total = 0
        for a, b in zip(f32_outs, q_outs):
            a, b = np.asarray(a), np.asarray(b)
            mismatch += int(np.sum(np.argmax(a, -1) != np.argmax(b, -1)))
            total += a.shape[0]
        return mismatch / max(1, total), "top1"
    num = den = 0.0
    for a, b in zip(f32_outs, q_outs):
        for la, lb in zip(jax.tree_util.tree_leaves(a),
                          jax.tree_util.tree_leaves(b)):
            la = np.asarray(la, np.float64)
            lb = np.asarray(lb, np.float64)
            num += float(np.sum((la - lb) ** 2))
            den += float(np.sum(la ** 2))
    return num / max(den, 1e-12), "output_mse"


def calibrate_and_quantize(
    name: str,
    fn: Callable,
    variables,
    calib_batches: Iterable,
    tolerance: float = 0.02,
    journal=None,
    select: Optional[Callable] = None,
) -> QuantizedModel:
    """Quantize ``variables`` and GATE the result on a representative
    batch stream: the f32 reference and the int8 function run the same
    batches, and the delta must clear ``tolerance`` or the int8 tree is
    refused. Every verdict is a typed ``quant_calibrated`` event.

    ``calib_batches``: an iterable of input arrays shaped like serving
    traffic (a handful is enough — the gate judges output drift, not
    activation ranges: weight-only PTQ needs no activation statistics).
    """
    batches = [np.asarray(b) for b in calib_batches]
    if not batches:
        raise ServeError(f"calibrate_and_quantize({name!r}) needs at least "
                         "one calibration batch")
    qvars, report = quantize_variables(variables, select=select)
    qfn = quantized_fn(fn)
    f32_outs = [jax.device_get(fn(variables, b)) for b in batches]
    q_outs = [jax.device_get(qfn(qvars, b)) for b in batches]
    delta, metric = _accuracy_delta(f32_outs, q_outs)
    accepted = bool(delta <= tolerance)
    if journal is not None:
        journal.write(
            "quant_calibrated", model=name, delta=float(round(delta, 6)),
            accepted=accepted, metric=metric, tolerance=float(tolerance),
            batches=len(batches),
            quantized_leaves=report["quantized_leaves"],
            compression=report["compression"])
    if not accepted:
        raise QuantizationRejected(
            f"int8 {name!r} failed the accuracy gate: {metric} delta "
            f"{delta:.4g} > tolerance {tolerance:g} over {len(batches)} "
            "calibration batches — serve the f32 engine and investigate "
            "(an outlier channel usually wants a per-layer exclusion)")
    return QuantizedModel(name, qfn, qvars, report, float(delta), metric,
                          float(tolerance))


# -- checkpoint sidecar round-trip -------------------------------------------

def scales_host_state(qvars) -> dict:
    """Per-channel scales as a JSON-serializable dict ('/'-joined path
    -> list of floats) for the crc32c checkpoint sidecar: the int8
    arrays ride the array checkpoint, the scales ride the sidecar, and
    :func:`apply_scales` re-marries them at restore."""
    out = {}

    def walk(node, path):
        if _is_quantized_leaf(node):
            out["/".join(path)] = [float(s)
                                   for s in np.asarray(node["scale"]).ravel()]
            return
        if isinstance(node, dict) or hasattr(node, "items"):
            for k, v in node.items():
                walk(v, path + (k,))

    walk(qvars, ())
    return out


def apply_scales(qvars, host_scales: dict):
    """The quantized tree with every scale replaced from sidecar host
    state; a path or length mismatch raises instead of silently serving
    mis-scaled weights."""
    seen = set()

    def walk(node, path):
        if _is_quantized_leaf(node):
            key = "/".join(path)
            if key not in host_scales:
                raise ServeError(
                    f"sidecar carries no scales for quantized leaf {key!r}")
            stored = np.asarray(host_scales[key], np.float32)
            if stored.size != np.asarray(node["scale"]).size:
                raise ServeError(
                    f"sidecar scales for {key!r} have {stored.size} "
                    f"channels, tree has {np.asarray(node['scale']).size}")
            seen.add(key)
            return {"q8": node["q8"],
                    "scale": stored.reshape(np.asarray(node["scale"]).shape)}
        if isinstance(node, dict) or hasattr(node, "items"):
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        return node

    out = walk(qvars, ())
    extra = set(host_scales) - seen
    if extra:
        raise ServeError(
            f"sidecar carries scales for unknown leaves {sorted(extra)}")
    return out
