"""Short real-hardware convergence run; records the loss curve as an artifact.

The reference commits multi-MB training logs as convergence evidence
(ResNet/pytorch/logs/resnet50-yanjiali-010919.log; "compare with other's
losses", YOLO/tensorflow/README.md:18). This is the executable equivalent
sized for CI-on-a-chip: N optimizer steps of the flagship ResNet-50 recipe
(bf16, s2d stem, SGD+momentum exactly as configs/resnet50) on a fixed
memorizable fixture, asserting the loss collapses, and writing the full curve
+ environment to artifacts/ for humans to diff between rounds.

    python -m deep_vision_tpu.tools.convergence_run [--steps 200] [--batch 64]

`--holdout` switches the fixture to a PROCEDURAL dataset with a train/val
split: class identity is a visual structure (oriented sinusoidal grating x
spatial frequency, under per-sample phase/position/noise jitter), so a model
can only score on the held-out split by learning the structure — memorizing
the train set scores chance on val. The artifact then also records val
top-1/top-5 against chance (the `validate`/`accuracy` evidence shape of
ResNet/pytorch/train.py:488-538, sized for one chip).
"""
from __future__ import annotations

import argparse
import json
import os
import time
from typing import Optional


def procedural_gratings(n: int, classes: int = 16, size: int = 112,
                        seed: int = 0):
    """(images, labels): class = (orientation, spatial frequency) pair.

    Per-sample random phase, center offset, amplitude and pixel noise make
    every image unique; the class-defining structure (angle in {0,45,90,135}
    deg x frequency in 4 steps) is all that separates classes.
    """
    import numpy as np

    rng = np.random.RandomState(seed)
    labels = rng.randint(0, classes, size=n)
    ys, xs = np.mgrid[0:size, 0:size].astype(np.float32) / size
    images = np.empty((n, size, size, 3), np.float32)
    for i, c in enumerate(labels):
        theta = (c % 4) * np.pi / 4
        freq = 4.0 + 3.0 * (c // 4)  # cycles per image: 4, 7, 10, 13
        phase = rng.uniform(0, 2 * np.pi)
        dx, dy = rng.uniform(-0.2, 0.2, size=2)
        amp = rng.uniform(0.35, 0.5)
        wave = np.sin(
            2 * np.pi * freq * ((xs - dx) * np.cos(theta)
                                + (ys - dy) * np.sin(theta)) + phase
        )
        img = 0.5 + amp * wave[..., None]
        img = img + rng.randn(size, size, 3).astype(np.float32) * 0.15
        images[i] = np.clip(img, 0.0, 1.0)
    return images, labels.astype(np.int32)


def _build_recipe(model_name: str, classes: int, sgd_lr: float,
                  adamw_lr: float):
    """(state, recipe string, prep fn): the shared model/optimizer setup.

    `prep` maps host float images (N, 112, 112, 3) to the model's input
    layout (the s2d stem's host half for resnet50, identity otherwise).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.data.transforms import space_to_depth
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train.optimizers import build_optimizer

    if model_name == "resnet50":
        model = get_model("resnet50", num_classes=classes, dtype=jnp.bfloat16,
                          stem="s2d")
        tx = build_optimizer("sgd", sgd_lr, momentum=0.9, weight_decay=1e-4)
        sample = jnp.ones((8, 56, 56, 12), jnp.float32)
        recipe = f"resnet50 (bf16, s2d stem, SGD {sgd_lr}/0.9/1e-4)"
        prep = lambda a: np.stack([space_to_depth(i) for i in a])
    else:  # the attention family: AdamW recipe on raw 112px inputs
        model = get_model(model_name, num_classes=classes, dtype=jnp.bfloat16)
        tx = build_optimizer("adamw", adamw_lr, weight_decay=1e-4)
        sample = jnp.ones((8, 112, 112, 3), jnp.float32)
        recipe = f"{model_name} (bf16, AdamW {adamw_lr}/1e-4)"
        prep = lambda a: a
    state = create_train_state(model, tx, sample, jax.random.PRNGKey(0))
    return state, recipe, prep


def _train_step(state, batch):
    """One classification train step (shared by run / run_holdout)."""
    import jax

    from deep_vision_tpu.losses.classification import classification_loss_fn

    def loss_fn(params):
        variables = {"params": params}
        # NB mutable=False, not []: flax returns (y, vars) for ANY list
        mutable = False
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
            mutable = ["batch_stats"]
        out = state.apply_fn(
            variables, batch["image"], train=True,
            rngs={"dropout": jax.random.fold_in(state.rng, state.step)},
            mutable=mutable)
        out, nms = out if mutable else (out, {})
        loss, _ = classification_loss_fn(out, batch)
        return loss, nms.get("batch_stats", {})

    (loss, bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        state.params)
    new_state = state.apply_gradients(grads)
    if state.batch_stats:
        new_state = new_state.replace(batch_stats=bs)
    return new_state, loss


def _write_artifact(out_path: str, result: dict) -> None:
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)


def run(steps: int = 200, batch: int = 64, classes: int = 64,
        model_name: str = "resnet50", out_path: Optional[str] = None) -> dict:
    out_path = out_path or f"artifacts/{model_name}_tpu_convergence.json"
    import jax
    import jax.numpy as jnp
    import numpy as np

    # fixed fixture: `batch` images / `classes` labels, memorizable in O(100)
    # steps — real-data ImageNet is not present in this environment, so the
    # evidence is "the full recipe optimizes on hardware", not accuracy parity
    rng = np.random.RandomState(0)
    imgs = rng.rand(batch, 112, 112, 3).astype(np.float32)
    state, recipe, prep = _build_recipe(model_name, classes,
                                        sgd_lr=0.05, adamw_lr=1e-3)
    batch_d = {
        "image": jnp.asarray(prep(imgs), jnp.bfloat16),
        "label": jnp.asarray(np.arange(batch) % classes, jnp.int32),
    }

    step = jax.jit(_train_step, donate_argnums=0)
    losses = []
    t0 = time.time()
    for i in range(steps):
        state, loss = step(state, batch_d)
        if i % 10 == 0 or i == steps - 1:
            losses.append((i, float(loss)))
    wall = time.time() - t0

    dev = jax.devices()[0]
    result = {
        "model": recipe,
        "device": f"{dev.platform}:{dev.device_kind}",
        "steps": steps,
        "batch": batch,
        "classes": classes,
        "wall_seconds": round(wall, 1),
        "loss_curve": [[i, round(l, 4)] for i, l in losses],
        "first_loss": round(losses[0][1], 4),
        "final_loss": round(losses[-1][1], 4),
    }
    _write_artifact(out_path, result)
    return result


def run_holdout(steps: int = 300, batch: int = 64, classes: int = 16,
                model_name: str = "resnet50", out_path: Optional[str] = None,
                n_train: int = 512, n_val: int = 256) -> dict:
    """Train on a procedural split, score the HELD-OUT split.

    Evidence of generalization, not memorization: val images are freshly
    sampled (different seed) from the same class-structure distribution.
    """
    out_path = out_path or f"artifacts/{model_name}_holdout.json"
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deep_vision_tpu.core.metrics import topk_accuracy

    tr_x, tr_y = procedural_gratings(n_train, classes, seed=0)
    va_x, va_y = procedural_gratings(n_val, classes, seed=1)
    # lower LRs than run(): generalizing a split is harder than memorizing
    # one fixed batch
    state, recipe, prep = _build_recipe(model_name, classes,
                                        sgd_lr=0.02, adamw_lr=3e-4)
    tr_x, va_x = prep(tr_x), prep(va_x)

    def eval_logits(state, images):
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        out = state.apply_fn(variables, images, train=False)
        return out[0] if isinstance(out, tuple) else out

    # device-resident dataset, indexed inside jit: through this rig's relay
    # a per-step host->device image transfer costs more than the step itself
    def sampled_step(state, data_x, data_y, idx):
        return _train_step(state, {"image": jnp.take(data_x, idx, axis=0),
                                   "label": jnp.take(data_y, idx, axis=0)})

    step = jax.jit(sampled_step, donate_argnums=0)
    eval_fn = jax.jit(eval_logits)
    data_x = jnp.asarray(tr_x, jnp.bfloat16)
    data_y = jnp.asarray(tr_y)

    rng = np.random.RandomState(7)
    losses = []
    t0 = time.time()
    for i in range(steps):
        idx = jnp.asarray(rng.randint(0, n_train, size=batch))
        state, loss = step(state, data_x, data_y, idx)
        if i % 10 == 0 or i == steps - 1:
            losses.append((i, float(loss)))
    wall = time.time() - t0

    def split_top1(x, y):
        # eval batch clamped to the split size: --batch larger than n_val
        # must not produce zero batches (mean of [] = NaN); the sub-batch
        # tail is dropped, n reports rows actually scored
        eb = min(batch, len(x))
        accs, n = [], 0
        for s in range(0, len(x) - eb + 1, eb):
            logits = eval_fn(state, jnp.asarray(x[s:s + eb], jnp.bfloat16))
            accs.append(topk_accuracy(logits, jnp.asarray(y[s:s + eb])))
            n += eb
        return (float(np.mean([float(a["top1"]) for a in accs])),
                float(np.mean([float(a["top5"]) for a in accs])), n)

    val_top1, val_top5, n_scored = split_top1(va_x, va_y)
    train_top1, _, _ = split_top1(tr_x, tr_y)

    dev = jax.devices()[0]
    result = {
        "model": recipe,
        "dataset": "procedural gratings: class = orientation x frequency, "
                   "per-sample phase/offset/noise jitter; val resampled "
                   "with a different seed",
        "device": f"{dev.platform}:{dev.device_kind}",
        "steps": steps,
        "batch": batch,
        "classes": classes,
        "n_train": n_train,
        "n_val": n_scored,
        "chance_top1": round(1.0 / classes, 4),
        "wall_seconds": round(wall, 1),
        "loss_curve": [[i, round(l, 4)] for i, l in losses],
        "first_loss": round(losses[0][1], 4),
        "final_loss": round(losses[-1][1], 4),
        "train_top1": round(train_top1, 4),
        "val_top1": round(val_top1, 4),
        "val_top5": round(val_top5, 4),
    }
    _write_artifact(out_path, result)
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--steps", type=int, default=None,
                   help="default 200 (memorization) / 300 (--holdout)")
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--model", default="resnet50",
                   help="resnet50 | vit_s16 | vmoe_s16")
    p.add_argument("--holdout", action="store_true",
                   help="procedural train/val split; report held-out top-1")
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)
    if args.holdout:
        out = args.out or f"artifacts/{args.model}_holdout.json"
        r = run_holdout(args.steps or 300, args.batch,
                        model_name=args.model, out_path=out)
        chance = r["chance_top1"]
        print(f"device={r['device']} final_loss={r['final_loss']} "
              f"train_top1={r['train_top1']} val_top1={r['val_top1']} "
              f"(chance {chance}) wall={r['wall_seconds']}s -> {out}")
        ok = r["val_top1"] >= 4 * chance
        print("GENERALIZED" if ok else "DID NOT GENERALIZE")
        return 0 if ok else 1
    out = args.out or f"artifacts/{args.model}_tpu_convergence.json"
    r = run(args.steps or 200, args.batch, model_name=args.model, out_path=out)
    print(f"device={r['device']} first={r['first_loss']} "
          f"final={r['final_loss']} wall={r['wall_seconds']}s -> {out}")
    ok = r["final_loss"] < 0.5 * r["first_loss"]
    print("CONVERGED" if ok else "DID NOT CONVERGE")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
