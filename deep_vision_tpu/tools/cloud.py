"""Post-training artifact upload: checkpoints -> object store.

The cloud-run hook from the reference's only deployment path
(Hourglass/tensorflow/main.py:50-65: google.cloud.storage blob upload after
training, destination echoed to /tmp/output.txt), generalized: `gs://` via
the google-cloud-storage client when importable else the gsutil CLI,
`s3://` via the aws CLI, and plain/`file://` paths via filesystem copy (the
testable local backend). Directories (orbax checkpoint step dirs) are
uploaded recursively.
"""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import List


def _walk(src: str) -> List[str]:
    if os.path.isfile(src):
        return [src]
    out = []
    for root, _, files in os.walk(src):
        out.extend(os.path.join(root, f) for f in files)
    return sorted(out)


def _gs_upload(src: str, dest: str) -> None:
    try:
        from google.cloud import storage  # type: ignore
    except ImportError:
        subprocess.run(["gsutil", "-m", "cp", "-r", src, dest], check=True)
        return
    bucket_name, _, prefix = dest[len("gs://"):].partition("/")
    bucket = storage.Client().bucket(bucket_name)
    base = os.path.dirname(src.rstrip("/"))
    for path in _walk(src):
        blob_name = os.path.join(prefix, os.path.relpath(path, base))
        bucket.blob(blob_name).upload_from_filename(path)


def upload_artifact(src: str, dest: str,
                    manifest_path: str = "/tmp/output.txt") -> str:
    """Upload `src` (file or directory) under `dest`; returns the final URI.

    Writes the URI to `manifest_path` the way the reference's trainer does
    (Hourglass/tensorflow/main.py:63-65), so cluster jobs can hand the model
    location to the next pipeline stage.
    """
    name = os.path.basename(src.rstrip("/"))
    if dest.startswith("gs://"):
        _gs_upload(src, dest)
        uri = f"{dest.rstrip('/')}/{name}"
    elif dest.startswith("s3://"):
        subprocess.run(
            ["aws", "s3", "cp", "--recursive" if os.path.isdir(src) else
             "--no-progress", src, f"{dest.rstrip('/')}/{name}"],
            check=True,
        )
        uri = f"{dest.rstrip('/')}/{name}"
    else:
        target_root = dest[len("file://"):] if dest.startswith("file://") else dest
        target = os.path.join(target_root, name)
        os.makedirs(target_root, exist_ok=True)
        if os.path.isdir(src):
            shutil.copytree(src, target, dirs_exist_ok=True)
        else:
            shutil.copy2(src, target)
        uri = target
    try:
        with open(manifest_path, "w") as f:
            f.write(uri + "\n")
    except OSError:
        pass  # manifest is best-effort (read-only /tmp in some sandboxes)
    return uri
