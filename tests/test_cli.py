"""Config registry + CLI tests (fast paths only; heavy models are smoke-tested
via `train.py --fake-data` out of band)."""
import os

import numpy as np
import pytest

from deep_vision_tpu.configs import CONFIG_REGISTRY, get_config
from deep_vision_tpu.models import get_model
from deep_vision_tpu.train_cli import build_dataloaders, build_trainer, main

pytestmark = pytest.mark.slow  # jit-heavy: excluded from the fast tier (`-m "not slow"`)


def test_every_config_resolves_to_a_model():
    # parity check: the registry covers the union of the reference's
    # training_config dicts (ResNet/pytorch/train.py:26-215 et al.)
    expected = {
        "lenet5", "alexnet1", "alexnet2", "vgg16", "vgg19", "inception1",
        "inception3", "resnet34", "resnet50", "resnet152", "resnet50v2",
        "mobilenet1", "shufflenet1", "yolov3_coco", "yolov3_voc",
        "hourglass_mpii", "centernet_coco", "dcgan_mnist", "cyclegan",
    }
    assert expected <= set(CONFIG_REGISTRY)
    for name, cfg in CONFIG_REGISTRY.items():
        if cfg.task in ("dcgan", "cyclegan"):
            continue
        kwargs = dict(cfg.model_kwargs)
        if cfg.task != "pose":
            kwargs["num_classes"] = cfg.num_classes
        assert get_model(cfg.model, **kwargs) is not None


def test_get_config_returns_copy():
    a = get_config("lenet5")
    a.epochs = 1
    assert CONFIG_REGISTRY["lenet5"].epochs == 50


@pytest.mark.parametrize("task,keys", [
    ("classification", {"image", "label"}),
    ("detection", {"image", "boxes", "classes"}),
    ("pose", {"image", "heatmap", "keypoints", "visibility"}),
    ("centernet", {"image", "boxes", "classes", "heatmap", "wh", "offset", "mask"}),
])
def test_fake_dataloaders_shapes(task, keys):
    name = {"classification": "lenet5", "detection": "yolov3_voc",
            "pose": "hourglass_mpii", "centernet": "centernet_coco"}[task]
    cfg = get_config(name)
    cfg.batch_size = 2
    train_fn, eval_fn = build_dataloaders(cfg, ".", fake=True, fake_batches=2,
                                          num_workers=1)
    batches = list(train_fn())
    assert len(batches) == 2
    assert set(batches[0]) == keys
    assert batches[0]["image"].shape == (2, *cfg.input_shape)
    if task == "centernet":
        s = cfg.input_shape[0] // 4
        assert batches[0]["heatmap"].shape == (2, s, s, cfg.num_classes)


def test_cli_lenet5_trains_and_resumes(tmp_path, mesh8):
    ck = str(tmp_path / "ck")
    rc = main(["-m", "lenet5", "--fake-data", "--epochs", "1",
               "--batch-size", "16", "--fake-batches", "2",
               "--ckpt-dir", ck])
    assert rc == 0
    rc = main(["-m", "lenet5", "--fake-data", "--epochs", "2",
               "--batch-size", "16", "--fake-batches", "2",
               "--ckpt-dir", ck, "-c", "auto"])
    assert rc == 0


def test_schedule_epoch_to_step_conversion():
    cfg = get_config("vgg16")
    from deep_vision_tpu.train_cli import _build_schedule

    sched = _build_schedule(cfg, steps_per_epoch=100)
    # StepLR(10 epochs, 0.5): constant within the first 10 epochs
    assert float(sched(0)) == pytest.approx(0.01)
    assert float(sched(999)) == pytest.approx(0.01)
    assert float(sched(1000)) == pytest.approx(0.005)


def test_cli_eval_only_classification(tmp_path, mesh8, capsys):
    from deep_vision_tpu.train_cli import main

    rc = main(["-m", "lenet5", "--fake-data", "--epochs", "1",
               "--batch-size", "16", "--ckpt-dir", str(tmp_path / "ck")])
    assert rc == 0
    rc = main(["-m", "lenet5", "--fake-data", "--batch-size", "16",
               "--ckpt-dir", str(tmp_path / "ck"), "-c", "auto",
               "--eval-only"])
    assert rc == 0
    assert "eval:" in capsys.readouterr().out


def test_cli_eval_only_detection(mesh8, capsys):
    """mAP path end-to-end via the CLI on fake data (untrained model: the
    metric just has to compute, not be good)."""
    from deep_vision_tpu.train_cli import main

    rc = main(["-m", "yolov3_voc", "--fake-data", "--fake-batches", "1",
               "--batch-size", "2", "--eval-only"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "mAP@.5=" in out


def test_cli_eval_only_pose(mesh8, capsys):
    from deep_vision_tpu.train_cli import main

    rc = main(["-m", "hourglass_mpii", "--fake-data", "--fake-batches", "1",
               "--batch-size", "2", "--eval-only"])
    assert rc == 0
    assert "PCK" in capsys.readouterr().out


def test_cli_eval_only_centernet(mesh8, capsys):
    from deep_vision_tpu.train_cli import main

    rc = main(["-m", "centernet_coco", "--fake-data", "--fake-batches", "1",
               "--batch-size", "2", "--eval-only"])
    assert rc == 0
    assert "mAP@.5=" in capsys.readouterr().out


def test_cli_eval_only_rejected_for_gans(capsys):
    from deep_vision_tpu.train_cli import main

    with pytest.raises(SystemExit):
        main(["-m", "dcgan_mnist", "--fake-data", "--eval-only"])


def test_mpii_records_pose_chain_end_to_end(tmp_path):
    """Records -> CropRoi -> swap-flip -> resize -> heatmaps, through the
    CLI's real (non-fake) pose dataloader wiring (VERDICT r2 missing #1):
    the batch the trainer would see has crop-relative heatmaps."""
    import json as _json

    import cv2

    from deep_vision_tpu.configs import get_config
    from deep_vision_tpu.tools.convert import main as convert_main
    from deep_vision_tpu.train_cli import build_dataloaders

    imgs = tmp_path / "images"
    os.makedirs(imgs)
    img = np.zeros((100, 200, 3), np.uint8)
    img[:, :, 1] = 128
    cv2.imwrite(str(imgs / "p.jpg"), img)
    # one person: visible joints spanning x[40,160] y[20,80], scale 0.5
    joints = [[40 + 8 * j, 20 + 4 * j] for j in range(16)]
    people = [{"image": "p.jpg", "joints": joints,
               "joints_vis": [1] * 16, "center": [100, 50], "scale": 0.5}]
    (tmp_path / "train.json").write_text(_json.dumps(people * 1))
    for prefix in ("train", "val"):
        convert_main([
            "mpii", "--json", str(tmp_path / "train.json"),
            "--images-dir", str(imgs), "--out-dir", str(tmp_path / "rec"),
            "--prefix", prefix, "--num-shards", "1", "--workers", "1",
        ])

    cfg = get_config("hourglass_mpii")
    cfg.batch_size = 1
    train_fn, eval_fn = build_dataloaders(
        cfg, str(tmp_path / "rec"), fake=False, fake_batches=0, num_workers=0
    )
    for fn, name in ((train_fn, "train"), (eval_fn, "eval")):
        (batch,) = list(fn())
        assert batch["image"].shape == (1, 256, 256, 3), name
        hm = np.asarray(batch["heatmap"])
        assert hm.shape == (1, 64, 64, 16), name
        # every visible joint scatters a gaussian: 16 nonzero channels
        # (grid peak >= exp(-0.25) ~ 0.78 at worst half-pixel offset)
        assert all(hm[0, :, :, j].max() > 0.5 for j in range(16)), name
    # eval chain is deterministic: two epochs, identical pixels
    (b1,) = list(eval_fn())
    (b2,) = list(eval_fn())
    np.testing.assert_array_equal(b1["image"], b2["image"])
