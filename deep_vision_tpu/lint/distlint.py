"""distlint: the DV2xx distributed-correctness pack.

Rides the jaxlint engine exactly like the DV1xx concurrency pack: one
RULES registry (rules.py merges DIST_RULES at import), one baseline,
one suppression syntax, one CLI. Where DV0xx encodes single-process
JAX discipline and DV1xx encodes lock discipline, DV2xx encodes the
repo's DISTRIBUTED contracts — the ones that so far lived in memory:

  DV201 hardcoded-platform-check — a string comparison against
        'tpu'/'cpu'/'gpu' (via jax.default_backend(), `.platform`, or
        a bare `platform` name) anywhere but core/backend.py. Platform
        is a routing decision; the registry owns it (ROADMAP item 4).
  DV202 unbounded-collective — a jax.experimental.multihost_utils
        call site outside parallel/multihost.py and resilience/
        rendezvous.py. Raw host collectives cannot name a dead peer,
        only hang on it; the PR 13 contract is that every host-level
        barrier/allgather is deadline-bounded by those wrappers.
        (Device-level lax.psum/ppermute inside shard_map bodies are a
        different animal and are not flagged.)
  DV203 unregistered-env-knob — an os.environ/os.getenv read of a
        DVT_* name outside core/knobs.py, or a knobs.get_*() call
        naming a knob the KNOBS registry does not declare. One
        registry, one mistype-raises parse contract.
  DV204 journal-schema-drift — a `journal.write("event", ...)` emitter
        whose event type has no tools/check_journal.py EVENT_FIELDS
        schema (and no allowlist entry). Replaces the hand-written
        per-PR emitter-vs-schema drift tests with one static pass.
  DV205 pspec-table-hygiene — a ShardingRules(...) table with
        non-literal patterns, a missing trailing catch-all, or a spec
        naming an axis parallel/mesh.py does not declare: the
        statically checkable half of ShardingRuleError. (The dynamic
        half — coverage floors, shadowing, dead patterns against real
        abstract trees — is tools/shard_check.py.)

Cross-file inputs (the check_journal schema table, the knob registry,
the mesh axis names) are read via AST from their source files, located
relative to this module — no jax import, no cwd dependence. The lint
cache (engine.py) folds those files into its pack fingerprint so a
schema edit invalidates cached DV204 results.
"""
from __future__ import annotations

import ast
import functools
import os
from typing import Dict, List, Optional, Set, Tuple

from deep_vision_tpu.lint.findings import Finding
from deep_vision_tpu.lint.jitctx import last_name

#: repo root, resolved from this file: deep_vision_tpu/lint/distlint.py
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: the one module allowed to compare platform strings (DV201)
PLATFORM_SANCTIONED = ("deep_vision_tpu/core/backend.py",)

#: the deadline-bounded wrapper modules (DV202): raw multihost_utils
#: call sites are legal HERE and nowhere else
COLLECTIVE_SANCTIONED = (
    "deep_vision_tpu/parallel/multihost.py",
    "deep_vision_tpu/resilience/rendezvous.py",
)

#: the knob registry module (DV203): raw DVT_* environ reads are legal
#: here and nowhere else
KNOBS_MODULE = "deep_vision_tpu/core/knobs.py"

#: event types a journal emitter may use WITHOUT a check_journal
#: --strict schema. Deliberately empty: an event worth emitting is
#: worth validating — add the schema, not an allowlist row.
DV204_ALLOWLIST: Set[str] = set()

_PLATFORM_STRINGS = ("tpu", "cpu", "gpu")

_HOST_COLLECTIVES = (
    "sync_global_devices",
    "process_allgather",
    "broadcast_one_to_all",
)

_KNOB_HELPERS = ("get_int", "get_float", "get_flag", "get_choice",
                 "get_str")


def _find(ctx, code: str, node: ast.AST, message: str,
          severity: str = "error") -> Finding:
    return Finding(code, message, ctx.relpath, getattr(node, "lineno", 0),
                   getattr(node, "col_offset", 0), severity,
                   ctx.symbol_at(node))


def _dotted(node: ast.AST) -> List[str]:
    """['jax', 'experimental', 'multihost_utils', 'sync_global_devices']
    for a nested Attribute chain; [] when the chain has a non-name
    root (a call result, a subscript)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    parts.reverse()
    return parts


def _module_str_constants(tree: ast.Module) -> Dict[str, str]:
    """Module-level NAME = 'literal' assignments (ENV_SPEC =
    'DVT_FAULT_SPEC' in resilience/faults.py) so constant-routed env
    reads resolve like literal ones."""
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


def _resolve_str(node: ast.AST,
                 consts: Dict[str, str]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


# -- DV201: hardcoded-platform-check ------------------------------------------

def _is_platform_expr(node: ast.AST) -> bool:
    """jax.default_backend() / backend.current_platform() /
    device.platform / bare `platform`."""
    if isinstance(node, ast.Call):
        name = last_name(node.func)
        return name in ("default_backend", "current_platform")
    if isinstance(node, ast.Attribute):
        return node.attr == "platform"
    if isinstance(node, ast.Name):
        return node.id == "platform"
    return False


def _platform_literals(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and node.value in _PLATFORM_STRINGS:
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and
                e.value in _PLATFORM_STRINGS]
    return []


def check_dv201(ctx) -> List[Finding]:
    if ctx.relpath in PLATFORM_SANCTIONED:
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left] + list(node.comparators)
        lits = [s for side in sides for s in _platform_literals(side)]
        if not lits or not any(_is_platform_expr(s) for s in sides):
            continue
        out.append(_find(
            ctx, "DV201", node,
            f"hardcoded platform check against {lits[0]!r} — platform "
            "is a routing decision: read a capability off "
            "core/backend.py get_backend() instead (is_tpu/"
            "pallas_interpret/BackendProfile)"))
    return out


# -- DV202: unbounded-collective ----------------------------------------------

def check_dv202(ctx) -> List[Finding]:
    if ctx.relpath in COLLECTIVE_SANCTIONED:
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _dotted(node.func)
        if not chain:
            continue
        if "multihost_utils" in chain or chain[-1] in _HOST_COLLECTIVES:
            out.append(_find(
                ctx, "DV202", node,
                f"raw host collective {'.'.join(chain)}() — a jax "
                "barrier cannot name a dead peer, only hang on it; "
                "route through the deadline-bounded wrappers in "
                "parallel/multihost.py (sync_hosts/agree_flag) or "
                "resilience/rendezvous.py"))
    return out


# -- DV203: unregistered-env-knob ---------------------------------------------

@functools.lru_cache(maxsize=4)
def _registered_knobs(knobs_path: Optional[str] = None) -> Set[str]:
    """Knob names declared in core/knobs.py, read via AST (every
    `_k("DVT_...")` first argument) so linting needs no import of the
    linted tree. Missing file (fixture repos) -> empty set."""
    path = knobs_path or os.path.join(_REPO_ROOT, KNOBS_MODULE)
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return set()
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and last_name(node.func) == "_k" \
                and node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            names.add(node.args[0].value)
    return names


def _environ_read_name(node: ast.Call,
                       consts: Dict[str, str]) -> Optional[str]:
    """The env-var name of an os.environ.get(...)/os.getenv(...) call,
    or None when the call is not an environ read."""
    chain = _dotted(node.func)
    if not chain or not node.args:
        return None
    is_read = (chain[-1] == "getenv"
               or (chain[-1] == "get" and "environ" in chain))
    if not is_read:
        return None
    return _resolve_str(node.args[0], consts)


def check_dv203(ctx) -> List[Finding]:
    if ctx.relpath == KNOBS_MODULE:
        return []
    out: List[Finding] = []
    consts = _module_str_constants(ctx.tree)
    registered = _registered_knobs()
    for node in ast.walk(ctx.tree):
        # raw reads: os.environ.get / os.getenv / os.environ[...]
        name = None
        site = node
        if isinstance(node, ast.Call):
            name = _environ_read_name(node, consts)
            if name is None:
                # knobs.get_*("DVT_X"): the name must be registered
                chain = _dotted(node.func)
                if chain and chain[-1] in _KNOB_HELPERS and node.args:
                    kname = _resolve_str(node.args[0], consts)
                    if kname and kname.startswith("DVT_") and \
                            registered and kname not in registered:
                        out.append(_find(
                            ctx, "DV203", node,
                            f"knob {kname} is not declared in "
                            "core/knobs.py KNOBS — register it (name, "
                            "kind, default, doc) before reading it"))
                continue
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load):
            chain = _dotted(node.value)
            if chain and chain[-1] == "environ":
                name = _resolve_str(node.slice, consts)
        if name and name.startswith("DVT_"):
            out.append(_find(
                ctx, "DV203", site,
                f"raw environ read of {name} — every DVT_* knob goes "
                "through core/knobs.py (get_int/get_float/get_flag/"
                "get_choice/get_str): one registry, one mistype-raises "
                "parse contract"))
    return out


# -- DV204: journal-schema-drift ----------------------------------------------

@functools.lru_cache(maxsize=4)
def _schema_events(schema_path: Optional[str] = None) -> Set[str]:
    """Event types with a check_journal --strict schema: the keys of
    the EVENT_FIELDS dict in tools/check_journal.py, read via AST.
    Empty set when the file is missing (fixture repos) — the rule then
    stays silent rather than flagging everything."""
    path = schema_path or os.path.join(_REPO_ROOT, "tools",
                                       "check_journal.py")
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "EVENT_FIELDS" and \
                isinstance(node.value, ast.Dict):
            return {k.value for k in node.value.keys
                    if isinstance(k, ast.Constant) and
                    isinstance(k.value, str)}
    return set()


def _is_journal_write(ctx, node: ast.Call) -> bool:
    """journal.write(...) / self.journal.write(...) / _journal.write(...)
    — plus self.write(...) inside a *Journal class (obs/journal.py's
    RunJournal emitting its own typed rows)."""
    if not isinstance(node.func, ast.Attribute) or \
            node.func.attr != "write":
        return False
    recv = last_name(node.func.value)
    if recv in ("journal", "_journal"):
        return True
    if recv == "self":
        qual = ctx.symbol_at(node)
        return "Journal" in qual.split(".")[0] if qual else False
    return False


def _forwarding_wrappers(ctx) -> Dict[str, ast.FunctionDef]:
    """Methods that forward their first event parameter to
    journal.write (the `def _event(self, event, **fields): ...
    journal.write(event, ...)` guard idiom in excache/data-service/
    rendezvous). Their LITERAL call sites are the real emitters — DV204
    checks those and exempts the wrapper's own dynamic write."""
    out: Dict[str, ast.FunctionDef] = {}
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        params = [a.arg for a in fn.args.args if a.arg != "self"]
        if not params:
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    _is_journal_write(ctx, node) and node.args and \
                    isinstance(node.args[0], ast.Name) and \
                    node.args[0].id == params[0]:
                out[fn.name] = fn
                break
    return out


def check_dv204(ctx) -> List[Finding]:
    events = _schema_events()
    if not events:
        return []
    out: List[Finding] = []
    wrappers = _forwarding_wrappers(ctx)
    wrapped_writes = {
        id(node)
        for fn in wrappers.values()
        for node in ast.walk(fn)
        if isinstance(node, ast.Call) and _is_journal_write(ctx, node)
    }
    # EVENT_HOST_LOST = "host_lost" module constants count as literal
    consts = _module_str_constants(ctx.tree)

    def check_event(node: ast.Call, arg: ast.AST) -> None:
        event = _resolve_str(arg, consts)
        if event is None:
            out.append(_find(
                ctx, "DV204", node,
                "journal.write with a dynamic event type cannot be "
                "schema-checked — emit literal event types, or "
                "suppress with a reason where the dynamism is the "
                "point"))
            return
        if event in events or event in DV204_ALLOWLIST:
            return
        out.append(_find(
            ctx, "DV204", node,
            f"journal event {event!r} has no tools/check_journal.py "
            "--strict schema — add an EVENT_FIELDS entry (or a "
            "DV204_ALLOWLIST row) so drift fails the gate, not a "
            "post-mortem"))

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_journal_write(ctx, node):
            # the dynamic write INSIDE a recognized forwarding wrapper
            # is plumbing, not an emitter — its call sites are checked
            if id(node) in wrapped_writes:
                continue
            if node.args:
                check_event(node, node.args[0])
            continue
        # literal call sites of a forwarding wrapper ARE emitters
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in wrappers and node.args:
            check_event(node, node.args[0])
    return out


# -- DV205: pspec-table-hygiene -----------------------------------------------

@functools.lru_cache(maxsize=4)
def _mesh_axes(mesh_path: Optional[str] = None) -> Set[str]:
    """Axis names the curated mesh declares: every module-level
    `*_AXIS = '...'` constant in parallel/mesh.py."""
    path = mesh_path or os.path.join(
        _REPO_ROOT, "deep_vision_tpu", "parallel", "mesh.py")
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return set()
    return {v for k, v in _module_str_constants(tree).items()
            if k.endswith("_AXIS")}


class _Unresolvable(Exception):
    def __init__(self, node: ast.AST):
        self.node = node


def _table_assigns(tree: ast.Module) -> Dict[str, ast.Call]:
    """NAME -> ShardingRules(...) call for module-level table
    assignments, so `VIT_RULES.rules` splices resolve."""
    out: Dict[str, ast.Call] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call) and \
                last_name(node.value.func) == "ShardingRules":
            out[node.targets[0].id] = node.value
    return out


def _rules_arg(call: ast.Call) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "rules":
            return kw.value
    if len(call.args) >= 2:  # ShardingRules(name, rules, ...)
        return call.args[1]
    return None


def _resolve_rule_pairs(node: ast.AST, tables: Dict[str, ast.Call],
                        depth: int = 0) -> List[Tuple[ast.AST, ast.AST]]:
    """-> [(pattern_node, spec_node), ...] with table-reference and
    tuple-concatenation splicing; raises _Unresolvable at anything
    the AST cannot prove."""
    if depth > 8:
        raise _Unresolvable(node)
    if isinstance(node, (ast.Tuple, ast.List)):
        pairs = []
        for elt in node.elts:
            if isinstance(elt, (ast.Tuple, ast.List)) and \
                    len(elt.elts) == 2:
                pairs.append((elt.elts[0], elt.elts[1]))
            else:
                raise _Unresolvable(elt)
        return pairs
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return (_resolve_rule_pairs(node.left, tables, depth + 1)
                + _resolve_rule_pairs(node.right, tables, depth + 1))
    # VIT_RULES.rules — splice another curated table
    if isinstance(node, ast.Attribute) and node.attr == "rules" and \
            isinstance(node.value, ast.Name) and \
            node.value.id in tables:
        inner = _rules_arg(tables[node.value.id])
        if inner is None:
            raise _Unresolvable(node)
        return _resolve_rule_pairs(inner, tables, depth + 1)
    raise _Unresolvable(node)


def _spec_axes(spec: ast.AST,
               consts: Dict[str, str]) -> Optional[List[str]]:
    """Axis names a spec literal uses; None when the spec is not a
    literal tuple of None/str/axis-constant entries."""
    if not isinstance(spec, (ast.Tuple, ast.List)):
        return None
    axes: List[str] = []
    for entry in spec.elts:
        if isinstance(entry, ast.Constant) and entry.value is None:
            continue
        s = _resolve_str(entry, consts)
        if s is not None:
            axes.append(s)
            continue
        if isinstance(entry, (ast.Tuple, ast.List)):
            for sub in entry.elts:
                s = _resolve_str(sub, consts)
                if s is None:
                    return None
                axes.append(s)
            continue
        return None
    return axes


def _import_aliases(tree: ast.Module) -> Dict[str, str]:
    """`from deep_vision_tpu.parallel.mesh import DATA_AXIS as D` ->
    {'D': 'DATA_AXIS'} (identity when unaliased)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                out[alias.asname or alias.name] = alias.name
    return out


@functools.lru_cache(maxsize=4)
def _mesh_axis_constants(mesh_path: Optional[str] = None) -> Dict[str, str]:
    path = mesh_path or os.path.join(
        _REPO_ROOT, "deep_vision_tpu", "parallel", "mesh.py")
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return {}
    return {k: v for k, v in _module_str_constants(tree).items()
            if k.endswith("_AXIS")}


def check_dv205(ctx) -> List[Finding]:
    out: List[Finding] = []
    tables = _table_assigns(ctx.tree)
    # names usable inside specs: module string constants plus imported
    # mesh axis constants (DATA_AXIS/MODEL_AXIS), resolved to their
    # declared values
    consts = dict(_module_str_constants(ctx.tree))
    axis_consts = _mesh_axis_constants()
    for local, imported in _import_aliases(ctx.tree).items():
        if imported in axis_consts:
            consts[local] = axis_consts[imported]
    declared = _mesh_axes()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or \
                last_name(node.func) != "ShardingRules":
            continue
        rules = _rules_arg(node)
        if rules is None:
            continue  # ShardingRules() with no rules refuses at runtime
        try:
            pairs = _resolve_rule_pairs(rules, tables)
        except _Unresolvable as e:
            out.append(_find(
                ctx, "DV205", e.node,
                "sharding table rules are not statically resolvable — "
                "tables are audited artifacts: literal (pattern, spec) "
                "tuples (concatenation of other curated tables' "
                "`.rules` is fine)"))
            continue
        last_pattern = None
        for pat_node, spec_node in pairs:
            pat = _resolve_str(pat_node, consts)
            if pat is None:
                out.append(_find(
                    ctx, "DV205", pat_node,
                    "sharding rule pattern is not a string literal — "
                    "a pattern that cannot be read cannot be "
                    "reviewed"))
                continue
            last_pattern = pat
            axes = _spec_axes(spec_node, consts)
            if axes is None:
                out.append(_find(
                    ctx, "DV205", spec_node,
                    f"rule {pat!r}: spec is not a literal tuple of "
                    "None/axis-name entries"))
                continue
            if declared:
                for axis in axes:
                    if axis not in declared:
                        out.append(_find(
                            ctx, "DV205", spec_node,
                            f"rule {pat!r} names mesh axis {axis!r} "
                            "but parallel/mesh.py declares only "
                            f"{sorted(declared)} — an unknown axis "
                            "refuses at resolve time on every mesh"))
        if pairs and last_pattern is not None and last_pattern != "*":
            out.append(_find(
                ctx, "DV205", node,
                f"sharding table has no trailing catch-all: the last "
                f"rule is {last_pattern!r}, not '*' — a leaf no rule "
                "covers must be a decision, not an accident"))
    return out


# -- registry -----------------------------------------------------------------

DIST_RULES = {
    "DV201": ("hardcoded-platform-check", "error", check_dv201,
              "platform string comparison outside the core/backend.py "
              "registry"),
    "DV202": ("unbounded-collective", "error", check_dv202,
              "raw multihost collective outside the deadline-bounded "
              "multihost/rendezvous wrappers"),
    "DV203": ("unregistered-env-knob", "error", check_dv203,
              "DVT_* env read bypassing (or missing from) the "
              "core/knobs.py registry"),
    "DV204": ("journal-schema-drift", "error", check_dv204,
              "journal event type without a check_journal --strict "
              "schema"),
    "DV205": ("pspec-table-hygiene", "error", check_dv205,
              "sharding table with non-literal patterns, missing "
              "catch-all, or unknown mesh axis"),
}
