"""Expert parallelism: mixture-of-experts FFN with all_to_all dispatch.

The reference is a dense CNN zoo with no conditional computation (SURVEY.md
§2), but expert parallelism is part of this framework's first-class
distributed story (DP x TP x PP x SP x EP) — vision MoEs (V-MoE) scale
exactly this way. Design is the GShard/Switch einsum formulation, which is
the TPU-native one: routing becomes two dense einsums against a one-hot
dispatch tensor (MXU work, static shapes, no gather/scatter), and the only
communication is a pair of `jax.lax.all_to_all` collectives that ride ICI —
tokens travel to the devices holding their expert and back.

Layout: tokens sharded over `axis_name` (each device routes its local
tokens), experts sharded over the same axis (each device owns E/n experts).
Capacity is static (TPU shapes must be): each expert accepts at most C
tokens per device per step; overflow tokens fall through the residual
connection untouched — the standard Switch-Transformer semantics.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deep_vision_tpu.parallel.mesh import DATA_AXIS


def expert_ffn(params, x):
    """Default expert: 2-layer GELU MLP. params: {'w1','b1','w2','b2'}."""
    h = jax.nn.gelu(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def _top1_dispatch(gates, capacity: int):
    """Switch top-1 routing -> (dispatch, combine) tensors.

    gates: (T, E) softmax router outputs.
    dispatch: (T, E, C) one-hot — token t occupies slot c of expert e.
    combine:  (T, E, C) = dispatch * gate prob (the output mixing weights).
    Tokens beyond an expert's capacity get an all-zero dispatch row.
    """
    t, e = gates.shape
    expert = jnp.argmax(gates, axis=-1)  # (T,)
    onehot = jax.nn.one_hot(expert, e, dtype=gates.dtype)  # (T, E)
    # position of each token within its expert's queue (0-based, in token
    # order — the deterministic tie-break the einsum formulation gives)
    pos = jnp.cumsum(onehot, axis=0) - onehot  # (T, E)
    keep = onehot * (pos < capacity)  # drop overflow
    slot = jax.nn.one_hot(
        jnp.sum(pos * onehot, axis=-1).astype(jnp.int32), capacity,
        dtype=gates.dtype,
    )  # (T, C)
    dispatch = keep[:, :, None] * slot[:, None, :]  # (T, E, C)
    prob = jnp.sum(gates * onehot, axis=-1)  # (T,) chosen-expert prob
    combine = dispatch * prob[:, None, None]
    return dispatch, combine


def _moe_local(router_w, expert_params, x, *, axis_name: str, capacity: int,
               expert_fn: Callable, n_experts: int):
    """Per-device body (under shard_map). x: (T_loc, D) local tokens."""
    n = jax.lax.psum(1, axis_name)
    e_loc = n_experts // n
    # route in f32 regardless of activation dtype (matching models/vit.py
    # MoeMlp): softmax + argmax over logits are precision-sensitive, and a
    # bf16 near-tie argmaxing to a different expert here than in the
    # in-model path would break checkpoint-deploy equivalence. The f32
    # gates feed dispatch (argmax inside); the resulting one-hot tensors
    # are cast back so expert compute stays in the activation dtype.
    gates = jax.nn.softmax(
        x.astype(jnp.float32) @ router_w.astype(jnp.float32)
    )  # (T_loc, E) — router replicated
    dispatch, combine = _top1_dispatch(gates, capacity)
    dispatch = dispatch.astype(x.dtype)
    combine = combine.astype(x.dtype)
    # pack: (E, C, D) expert inputs from the local tokens
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)
    # all_to_all #1: split the global-expert dim across devices, concat the
    # senders -> (E_loc, n, C, D): every device's slots for MY experts
    expert_in = expert_in.reshape(n, e_loc, capacity, -1)
    expert_in = jax.lax.all_to_all(
        expert_in, axis_name, split_axis=0, concat_axis=0, tiled=False
    )  # (n, E_loc, C, D) with leading dim = source device
    expert_in = expert_in.transpose(1, 0, 2, 3).reshape(
        e_loc, n * capacity, -1
    )
    # local experts run on their (n*C, D) batch — vmap over the expert dim,
    # each expert its own params slice
    expert_out = jax.vmap(expert_fn)(expert_params, expert_in)
    # all_to_all #2: route results back to the token-owning devices
    expert_out = expert_out.reshape(e_loc, n, capacity, -1).transpose(
        1, 0, 2, 3
    )
    expert_out = jax.lax.all_to_all(
        expert_out, axis_name, split_axis=0, concat_axis=0, tiled=False
    ).reshape(n_experts, capacity, -1)
    # unpack + mix; dropped tokens contribute 0 (pure residual pass-through)
    return jnp.einsum("tec,ecd->td", combine, expert_out)


def moe_ffn(
    router_w,
    expert_params,
    x,
    mesh: Mesh,
    *,
    capacity: int,
    expert_fn: Callable = expert_ffn,
    axis_name: str = DATA_AXIS,
):
    """Expert-parallel top-1 MoE layer over tokens sharded on `axis_name`.

    router_w: (D, E) routing weights (replicated).
    expert_params: pytree whose leaves have leading dim E, sharded over
    `axis_name` (device i holds experts [i*E/n, (i+1)*E/n)).
    x: (T, D) global tokens, T divisible by the axis size.
    capacity: per-expert, per-device slot count C. The output adds to a
    residual stream: dropped (over-capacity) tokens return zeros.
    """
    n = mesh.shape[axis_name]
    e = router_w.shape[-1]
    if e % n != 0:
        raise ValueError(f"{e} experts not divisible over {n} devices")
    body = functools.partial(
        _moe_local,
        axis_name=axis_name,
        capacity=capacity,
        expert_fn=expert_fn,
        n_experts=e,
    )
    expert_specs = jax.tree_util.tree_map(
        lambda p: P(axis_name, *([None] * (p.ndim - 1))), expert_params
    )
    mapped = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), expert_specs, P(axis_name)),
        out_specs=P(axis_name),
    )
    return mapped(router_w, expert_params, x)


def expert_param_sharding(mesh: Mesh, expert_params,
                          axis_name: str = DATA_AXIS):
    """Shard the leading (expert) dim of every leaf over `axis_name`."""
    return jax.tree_util.tree_map(
        lambda p: NamedSharding(mesh, P(axis_name, *([None] * (p.ndim - 1)))),
        expert_params,
    )


def load_balancing_loss(gates) -> jax.Array:
    """Switch-Transformer auxiliary loss: E * sum_e f_e * P_e.

    gates: (T, E) softmax router outputs. f_e is the fraction of tokens
    whose argmax picks expert e, P_e the mean router probability for e;
    minimized (== 1) when routing is uniform. Add `aux_weight *
    load_balancing_loss(gates)` to the task loss when training a router —
    without it top-1 routing collapses onto a few experts and the rest of
    the capacity (and the all_to_all bandwidth) idles.
    """
    t, e = gates.shape
    choice = jnp.argmax(gates, axis=-1)
    f = jnp.mean(jax.nn.one_hot(choice, e, dtype=gates.dtype), axis=0)
    p = jnp.mean(gates, axis=0)
    return e * jnp.sum(f * p)


def moe_ffn_dense(router_w, expert_params, x, *,
                  expert_fn: Callable = expert_ffn):
    """Single-device reference: every expert on all tokens (golden for tests).

    No capacity limit — equals `moe_ffn` exactly when capacity >= the
    busiest expert's per-device load.
    """
    gates = jax.nn.softmax(
        x.astype(jnp.float32) @ router_w.astype(jnp.float32)
    )  # (T, E) — f32 routing + argmax, as _moe_local / MoeMlp
    choice = jnp.argmax(gates, axis=-1)
    prob = jnp.take_along_axis(gates, choice[:, None], axis=-1).astype(x.dtype)
    all_out = jax.vmap(expert_fn, in_axes=(0, None))(expert_params, x)
    # (E, T, D) -> pick each token's expert
    picked = jnp.take_along_axis(
        all_out, choice[None, :, None], axis=0
    )[0]
    return picked * prob
