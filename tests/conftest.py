"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax import.

This is the pjit analog of the reference's CPU-MirroredStrategy trick
("CPU or single GPU also works", YOLO/tensorflow/README.md:2): multi-device
sharding semantics are exercised without TPU hardware.
"""
import os

# hard-set: the shell may carry JAX_PLATFORMS=axon (real TPU); tests always
# run on the virtual 8-device CPU mesh. The axon sitecustomize imports jax at
# interpreter startup, so the env var alone is read too early — update the
# config explicitly as well.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from deep_vision_tpu.parallel import create_mesh

    assert len(jax.devices()) == 8
    return create_mesh()


@pytest.fixture(scope="session")
def mesh4x2():
    from deep_vision_tpu.parallel import create_mesh

    return create_mesh(data=4, model=2)
