"""Environment preflight: fail in seconds, not minutes.

    python -m deep_vision_tpu.tools.preflight [--ckpt-dir DIR]
        [--mesh-data N] [--mesh-model M] [--expect-devices N]
        [--expect-hosts N --rendezvous-dir DIR [--host-id ID]]
        [--budget SECONDS] [--json]

Every accelerator-layer failure in the repo's own run history burned
minutes before dying: MULTICHIP_r01 spent ~4 minutes compiling before a
libtpu client/terminal version skew killed the first dispatch, and the
BENCH_r04/r05 dead tunnels HUNG (no exception) until an external timeout
fired at rc=124. This preflight front-loads those verdicts:

  client_versions   jax vs jaxlib (major, minor) agreement — the
                    client-side half of a version skew
  backend           a trivial device op must complete within --budget,
                    run on a probe THREAD (a dead relay blocks in socket
                    recv forever; only a join timeout can see it). Any
                    error it raises is classified
                    (resilience.elastic.classify_backend_error): the
                    MULTICHIP_r01 FAILED_PRECONDITION surfaces here as
                    `version_skew` in seconds, before any real compile.
                    Pass detail reports N x device_kind + the platform
                    version string — the terminal half of the handshake.
  mesh_shape        the requested (data, model) layout resolves over the
                    live device count (and matches --expect-devices when
                    given): a MULTICHIP launch asking for {'data': 4,
                    'model': 2} on a degraded 6-chip slice fails here,
                    not in the partitioner.
  ckpt_dir          checkpoint-directory writability, probed with the
                    same tmp+fsync+rename shape the crc32c sidecar uses:
                    a read-only or mis-mounted volume fails before the
                    first epoch trains into an unsaveable run.
  excache           with --excache: the persistent executable cache dir
                    (core/excache.py) is probed end-to-end — writable
                    with the tmp+fsync+rename shape, a trivial compiled
                    executable AOT-round-trips (store -> load -> run,
                    proving this backend can serialize executables), and
                    a deliberately version-skewed entry is REFUSED (the
                    stale-entry detector works). A bad cache mount fails
                    here in seconds, not at the first warmup miss.
  rendezvous        with --expect-hosts: join the elastic rendezvous
                    (resilience/rendezvous.py) and run the join-time
                    client-version/platform-version exchange through
                    the coordinator. A version-skewed joiner — the
                    MULTICHIP_r01 failure, where a stale host burned 4
                    minutes of everyone's compile before dying — is
                    refused HERE, in seconds, with kind `version_skew`,
                    never admitted into a generation; a world that
                    cannot assemble --expect-hosts compatible members
                    within the budget fails as `timeout` naming who
                    showed up.

Runnable standalone (`make preflight`; exit 0 pass / 1 fail, one line
per check) and as the first act of `train_cli` (--skip-preflight opts
out). All checks are pure functions over injectable inputs so the
pass/fail classification is unit-testable without breaking hardware.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from dataclasses import asdict, dataclass
from typing import Callable, List, Optional, Tuple

from deep_vision_tpu.core import knobs
from deep_vision_tpu.resilience.elastic import (
    KIND_VERSION_SKEW,
    backend_alive,
)

#: default probe budget: a healthy backend answers a trivial op in
#: milliseconds (CPU) to ~a second (cold TPU client); a dead tunnel never
#: does. Env-overridable for slow relays (DVT_PREFLIGHT_BUDGET_S).
DEFAULT_BUDGET_S = knobs.get_float("DVT_PREFLIGHT_BUDGET_S")


@dataclass
class CheckResult:
    name: str
    ok: bool
    detail: str
    kind: str = ""  # failure classification (elastic.BACKEND_LOST_KINDS)
    elapsed_ms: float = 0.0


# -- the checks (pure over injectable inputs) ---------------------------------

def check_client_versions(jax_version: Optional[str] = None,
                          jaxlib_version: Optional[str] = None) -> CheckResult:
    """jax and jaxlib must agree on (major, minor): the client-side half
    of a version skew (the installed pair drifting apart is the usual way
    one side of the libtpu handshake goes stale)."""
    if jax_version is None or jaxlib_version is None:
        import jax
        import jaxlib

        jax_version = jax_version or jax.__version__
        jaxlib_version = jaxlib_version or jaxlib.__version__
    detail = f"jax {jax_version}, jaxlib {jaxlib_version}"

    def mm(v: str) -> Tuple[str, ...]:
        return tuple(v.split(".")[:2])

    if mm(jax_version) != mm(jaxlib_version):
        return CheckResult("client_versions", False,
                           detail + " — (major, minor) disagree",
                           kind=KIND_VERSION_SKEW)
    return CheckResult("client_versions", True, detail)


def check_backend(budget_s: float = DEFAULT_BUDGET_S,
                  probe: Optional[Callable] = None) -> CheckResult:
    """The liveness + handshake probe: one trivial device op, threaded.

    A hang (dead tunnel) is reported as `timeout`; a raised exception is
    classified from the exception OBJECT (the type gate applies) — the
    libtpu client/terminal skew raises FAILED_PRECONDITION on the first
    dispatch and lands here as `version_skew` seconds into the run
    instead of minutes."""
    ok, err, kind = backend_alive(budget_s, probe=probe, with_kind=True)
    if not ok:
        return CheckResult("backend", False, err, kind=kind)
    try:
        import jax

        devs = jax.devices()
        # the terminal half of the handshake: on TPU this is the libtpu
        # build string MULTICHIP_r01's skew error quoted
        version = str(getattr(getattr(devs[0], "client", None),
                              "platform_version", "") or "")
        detail = (f"{len(devs)} x {devs[0].device_kind} "
                  f"({devs[0].platform}"
                  + (f", {version.splitlines()[0]}" if version else "")
                  + ")")
    except Exception as e:  # probe passed but introspection is exotic
        detail = f"alive (introspection unavailable: {type(e).__name__})"
    return CheckResult("backend", True, detail)


def check_mesh_shape(n_devices: int, data: int = -1, model: int = 1,
                     expect_devices: Optional[int] = None) -> CheckResult:
    """Does the requested (data, model) layout resolve over `n_devices`?"""
    from deep_vision_tpu.parallel.mesh import MeshSpec

    if expect_devices is not None and n_devices != expect_devices:
        return CheckResult(
            "mesh_shape", False,
            f"expected {expect_devices} devices, found {n_devices} "
            "(degraded slice, or the wrong machine)")
    try:
        d, m = MeshSpec(data=data, model=model).resolve(n_devices)
    except ValueError as e:
        return CheckResult("mesh_shape", False, str(e))
    return CheckResult("mesh_shape", True,
                       f"{{'data': {d}, 'model': {m}}} over "
                       f"{n_devices} device(s)")


def check_ckpt_dir(path: str) -> CheckResult:
    """Writability probe with the sidecar's own durability shape
    (tmp + fsync + rename), cleaned up after itself."""
    probe = os.path.join(path, f".preflight-{os.getpid()}")
    tmp = probe + ".tmp"
    try:
        os.makedirs(path, exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(b"preflight")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, probe)
        with open(probe, "rb") as f:
            if f.read() != b"preflight":
                return CheckResult("ckpt_dir", False,
                                   f"{path}: read-back mismatch "
                                   "(corrupting filesystem?)")
    except OSError as e:
        return CheckResult("ckpt_dir", False,
                           f"{path}: {type(e).__name__}: {e}")
    finally:
        for p in (tmp, probe):
            try:
                os.remove(p)
            except OSError:
                pass
    return CheckResult("ckpt_dir", True, f"{path} writable (tmp+fsync+rename)")


def check_excache(path: str) -> CheckResult:
    """Probe the executable cache end-to-end: writability, AOT
    serialize/deserialize round-trip, stale-entry refusal. Probe entries
    are cleaned up after themselves (like the ckpt_dir probe)."""
    import json as _json

    import numpy as np

    from deep_vision_tpu.core.excache import ExecutableCache
    from deep_vision_tpu.obs.registry import Registry

    try:
        import jax

        os.makedirs(path, exist_ok=True)
        # private registry: a probe must not bump the run's excache
        # counters before the first real warmup
        cache = ExecutableCache(path, registry=Registry())
        f = jax.jit(lambda x: x * 2.0 + 1.0)
        lowered = f.lower(jax.ShapeDtypeStruct((8,), "float32"))
        text = lowered.as_text()
        key = cache.key_for(text)
        compiled = lowered.compile()
        cleanup = [key]
        try:
            if not cache.store(key, compiled, name="preflight-probe"):
                return CheckResult(
                    "excache", False,
                    f"{path}: store failed — dir unwritable, or this "
                    "backend cannot serialize executables (the cache "
                    "would never hit)")
            loaded = cache.load(key, lowered, name="preflight-probe")
            if loaded is None:
                return CheckResult(
                    "excache", False,
                    f"{path}: stored probe entry did not load back "
                    "(corrupting filesystem, or deserialize unsupported)")
            x = np.ones((8,), np.float32)
            if not np.array_equal(np.asarray(loaded(x)),
                                  np.asarray(compiled(x))):
                return CheckResult(
                    "excache", False,
                    f"{path}: round-tripped executable computes a "
                    "different answer — refuse this cache")
            # stale-entry detection: a version-skewed manifest must be
            # refused, never loaded (the never-load-stale contract)
            skew_key = cache.key_for(text + "\n; preflight-skew-probe")
            cleanup.append(skew_key)
            cache.store(skew_key, compiled, name="preflight-skew-probe")
            man = os.path.join(path, skew_key + ".json")
            doc = _json.load(open(man))
            doc["fingerprint"]["jax"] = "0.0.0-preflight-skew"
            with open(man, "w") as fh:
                fh.write(_json.dumps(doc))
            if cache.load(skew_key, lowered,
                          name="preflight-skew-probe") is not None:
                return CheckResult(
                    "excache", False,
                    f"{path}: version-skewed entry LOADED — stale-entry "
                    "detection is broken, refuse this cache",
                    kind=KIND_VERSION_SKEW)
        finally:
            for k in cleanup:
                for p in (os.path.join(path, k + ".exe"),
                          os.path.join(path, k + ".json")):
                    try:
                        os.remove(p)
                    except OSError:
                        pass
    except Exception as e:
        # any probe failure — an unwritable mount (OSError), a wedged
        # device erroring the probe compile/run (XlaRuntimeError), a
        # serialize quirk — must render as a FAIL line, never a
        # traceback breaking preflight's exit-0/1 contract (the same
        # hardening check_rendezvous needed)
        return CheckResult("excache", False,
                           f"{path}: {type(e).__name__}: {e}")
    n = len([f for f in os.listdir(path) if f.endswith(".json")])
    return CheckResult(
        "excache", True,
        f"{path} writable, AOT round-trip ok, stale entry refused "
        f"({n} cached entr{'y' if n == 1 else 'ies'})")


def check_sharding_tables() -> CheckResult:
    """Device-free semantic audit of the curated sharding tables
    (tools/shard_check.py): every family's table must still clear its
    coverage floor against an abstract eval_shape state tree. The
    108 -> 34 MULTICHIP coverage regression fails HERE, before any
    mesh is built or a single byte is compiled."""
    from deep_vision_tpu.tools.shard_check import FAMILIES, check_family

    fails: List[str] = []
    summary: List[str] = []
    for family in FAMILIES:
        try:
            report = check_family(family)
        except Exception as e:  # a broken table is a FAIL line, never a
            # traceback breaking preflight's exit-0/1 contract
            fails.append(f"{family}: {type(e).__name__}: {e}")
            continue
        summary.append(f"{family} {report['sharded']}/{report['min_sharded']}")
        if not report["ok"]:
            reasons = report["errors"] or [
                f"coverage {report['sharded']} < floor "
                f"{report['min_sharded']}"]
            fails.append(f"{family}: {reasons[0]}")
    if fails:
        return CheckResult("sharding_tables", False, "; ".join(fails))
    return CheckResult(
        "sharding_tables", True,
        "coverage floors hold abstractly (" + ", ".join(summary) + ")")


def host_versions() -> dict:
    """This host's side of the join-time version exchange: the jax/jaxlib
    client pair plus the backend's platform_version string (on TPU, the
    libtpu build the MULTICHIP_r01 skew error quoted). Pure dict so the
    handshake comparison (`rendezvous.versions_compatible`) is
    unit-testable with fabricated values."""
    out = {}
    try:
        import jax
        import jaxlib

        out["client_version"] = f"jax {jax.__version__}, " \
                                f"jaxlib {jaxlib.__version__}"
        devs = jax.devices()
        pv = str(getattr(getattr(devs[0], "client", None),
                         "platform_version", "") or "")
        if pv:
            out["platform_version"] = pv.splitlines()[0]
    except Exception:
        pass  # version-less members compare compatible (fail open on
        # missing introspection, closed on an actual mismatch)
    return out


def check_rendezvous(expect_hosts: int, rendezvous_dir: str,
                     host_id: Optional[str] = None,
                     budget_s: float = DEFAULT_BUDGET_S,
                     versions: Optional[dict] = None) -> CheckResult:
    """Join the elastic rendezvous and run the version handshake.

    The joiner writes its member record (client + platform versions
    embedded), and the incumbent world's reference versions are compared
    on every poll: a skew is refused in seconds as `version_skew` — the
    preflight teeth for the one backend failure `BackendSupervisor`
    correctly refuses to retry. On success the probe LEAVES again (drops
    its member record): preflight must not squat a membership slot the
    real run is about to claim."""
    from deep_vision_tpu.resilience.rendezvous import (
        HostLostError,
        Rendezvous,
        RendezvousError,
        RendezvousRefused,
        RendezvousTimeout,
    )

    versions = host_versions() if versions is None else versions
    host_id = host_id or f"preflight-{os.uname().nodename}-{os.getpid()}"
    r = Rendezvous(rendezvous_dir, host_id,
                   client_version=versions.get("client_version"),
                   platform_version=versions.get("platform_version"))
    try:
        view = r.join(expect_hosts=expect_hosts, timeout_s=budget_s)
    except RendezvousRefused as e:
        return CheckResult("rendezvous", False, str(e), kind=e.kind)
    except RendezvousTimeout as e:
        return CheckResult("rendezvous", False, str(e), kind="timeout")
    except RendezvousError as e:
        # e.g. HostLostError: a probe peer died mid-assembly — still a
        # one-line failed check, never an unhandled traceback breaking
        # preflight's exit-0/1 contract
        kind = "host_lost" if isinstance(e, HostLostError) else ""
        return CheckResult("rendezvous", False, str(e), kind=kind)
    finally:
        r.leave()
    return CheckResult(
        "rendezvous", True,
        f"world of {view.world_size} assembled at generation "
        f"{view.generation} (rank {view.rank}, versions agree)")


# -- the runner ----------------------------------------------------------------

def run_preflight(data: int = -1, model: int = 1,
                  expect_devices: Optional[int] = None,
                  ckpt_dir: Optional[str] = None,
                  budget_s: float = DEFAULT_BUDGET_S,
                  probe: Optional[Callable] = None,
                  expect_hosts: Optional[int] = None,
                  rendezvous_dir: Optional[str] = None,
                  host_id: Optional[str] = None,
                  excache_dir: Optional[str] = None,
                  shard_tables: bool = True,
                  journal=None) -> Tuple[bool, List[CheckResult]]:
    """Run every applicable check; returns (all_ok, results).

    Ordering matters: the backend probe runs FIRST because when it fails
    nothing downstream (device count, mesh resolve) is meaningful — those
    checks are skipped rather than cascading the same root cause."""
    results: List[CheckResult] = []

    def run(fn, *args, **kw) -> CheckResult:
        t0 = time.perf_counter()
        r = fn(*args, **kw)
        r.elapsed_ms = round((time.perf_counter() - t0) * 1e3, 1)
        results.append(r)
        return r

    run(check_client_versions)
    backend = run(check_backend, budget_s=budget_s, probe=probe)
    if backend.ok:
        import jax

        run(check_mesh_shape, len(jax.devices()), data=data, model=model,
            expect_devices=expect_devices)
    if shard_tables:
        # device-free (pure eval_shape): runs even when the backend
        # probe failed — a gutted table is reportable regardless
        run(check_sharding_tables)
    if ckpt_dir:
        run(check_ckpt_dir, ckpt_dir)
    if excache_dir and backend.ok:
        # the probe compiles a trivial executable, so a dead backend
        # already failed above and would only cascade here
        run(check_excache, excache_dir)
    if expect_hosts is not None:
        if not rendezvous_dir:
            results.append(CheckResult(
                "rendezvous", False,
                "--expect-hosts needs --rendezvous-dir (the shared "
                "coordination directory every host mounts)"))
        elif backend.ok:
            # version exchange needs the backend's platform_version (the
            # terminal half of the handshake); a dead backend already
            # failed above and would only cascade here
            run(check_rendezvous, expect_hosts, rendezvous_dir,
                host_id=host_id, budget_s=budget_s)
    ok = all(r.ok for r in results)
    if journal is not None:
        try:
            journal.write("note", note="preflight",
                          ok=ok, checks=[asdict(r) for r in results])
        except Exception:
            pass
    return ok, results


def render(results: List[CheckResult], out=sys.stderr) -> None:
    for r in results:
        verdict = "PASS" if r.ok else "FAIL"
        kind = f" [{r.kind}]" if r.kind else ""
        print(f"preflight: {verdict} {r.name}{kind} — {r.detail} "
              f"({r.elapsed_ms:.0f} ms)", file=out)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--ckpt-dir", default=None,
                   help="also probe this checkpoint dir for writability")
    p.add_argument("--mesh-data", type=int, default=-1,
                   help="requested data-axis size (-1: all remaining)")
    p.add_argument("--mesh-model", type=int, default=1,
                   help="requested model-axis size")
    p.add_argument("--expect-devices", type=int, default=None,
                   help="fail unless exactly this many devices are live")
    p.add_argument("--expect-hosts", type=int, default=None,
                   help="join the elastic rendezvous and fail unless this "
                        "many version-compatible hosts assemble (a skewed "
                        "joiner is refused as version_skew in seconds)")
    p.add_argument("--rendezvous-dir", default=None,
                   help="shared rendezvous directory (with --expect-hosts)")
    p.add_argument("--host-id", default=None,
                   help="this host's rendezvous member id (default: a "
                        "probe-scoped id that leaves after the check)")
    p.add_argument("--excache", default=None, metavar="DIR",
                   help="also probe this persistent executable-cache dir "
                        "(writability, AOT round-trip, stale-entry "
                        "refusal — core/excache.py)")
    p.add_argument("--no-shard-check", action="store_true",
                   help="skip the device-free sharding-table audit "
                        "(tools/shard_check.py)")
    p.add_argument("--budget", type=float, default=DEFAULT_BUDGET_S,
                   help="seconds the backend probe may take before the "
                        "tunnel is declared dead")
    p.add_argument("--json", action="store_true",
                   help="print one machine-readable JSON line to stdout")
    args = p.parse_args(argv)
    ok, results = run_preflight(
        data=args.mesh_data, model=args.mesh_model,
        expect_devices=args.expect_devices, ckpt_dir=args.ckpt_dir,
        budget_s=args.budget, expect_hosts=args.expect_hosts,
        rendezvous_dir=args.rendezvous_dir, host_id=args.host_id,
        excache_dir=args.excache,
        shard_tables=not args.no_shard_check,
    )
    render(results)
    if args.json:
        print(json.dumps({"ok": ok,
                          "checks": [asdict(r) for r in results]}))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
