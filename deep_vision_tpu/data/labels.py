"""Host-side training-label builders for dense-prediction tasks.

Parity targets:
- Pose heatmaps: `generate_2d_guassian`/`make_heatmaps`
  (Hourglass/tensorflow/preprocess.py:91-173) — 64x64xK gaussian heatmaps
  from normalized keypoints, visibility-aware, 7x7 patch semantics
  generalized to a full vectorized gaussian.
- CenterNet targets: COMPLETED here — the reference's label generation
  early-returns zeros (ObjectsAsPoints/tensorflow/preprocess.py:129-147,
  SURVEY.md §2.9). Implemented from the ObjectsAsPoints paper: per-class
  center gaussians with IoU-derived radius, wh + sub-pixel offset at centers.

Numpy on purpose: these run in DataLoader worker threads; the device-side
jax twins live in ops/heatmaps.py (used when label-gen is fused into the
jitted step, as yolo_train_loss_fn does for detection).
"""
from __future__ import annotations

import numpy as np


def gaussian_2d(height: int, width: int, cx: float, cy: float, sigma: float):
    """Dense 2-D gaussian peaked at (cx, cy), grid coords."""
    ys = np.arange(height, dtype=np.float32)[:, None]
    xs = np.arange(width, dtype=np.float32)[None, :]
    return np.exp(-((xs - cx) ** 2 + (ys - cy) ** 2) / (2.0 * sigma ** 2))


def make_pose_heatmaps(sample: dict, size: int = 64, sigma: float = 1.0,
                       num_joints: int = 16) -> dict:
    """Add 'heatmap' (size, size, J) from normalized 'keypoints' (J, 2) +
    'visibility' (J,). Invisible joints get all-zero maps
    (visibility-aware scatter, Hourglass/tensorflow/preprocess.py:158-173)."""
    kp = np.asarray(sample["keypoints"], np.float32)
    vis = np.asarray(
        sample.get("visibility", np.ones((len(kp),), np.float32)), np.float32
    )
    hm = np.zeros((size, size, num_joints), np.float32)
    for j in range(min(num_joints, len(kp))):
        x, y = kp[j]
        if vis[j] <= 0 or not (0.0 <= x <= 1.0 and 0.0 <= y <= 1.0):
            continue
        hm[:, :, j] = gaussian_2d(size, size, x * (size - 1), y * (size - 1), sigma)
    sample["heatmap"] = hm
    return sample


def centernet_radius(h: float, w: float, min_overlap: float = 0.7) -> float:
    """Gaussian radius such that corners shifted by r keep IoU >= min_overlap
    (CornerNet derivation used by ObjectsAsPoints)."""
    a1, b1 = 1.0, h + w
    c1 = w * h * (1 - min_overlap) / (1 + min_overlap)
    r1 = (b1 - np.sqrt(max(b1 ** 2 - 4 * a1 * c1, 0.0))) / 2
    a2, b2 = 4.0, 2 * (h + w)
    c2 = (1 - min_overlap) * w * h
    r2 = (b2 - np.sqrt(max(b2 ** 2 - 4 * a2 * c2, 0.0))) / 2
    a3, b3 = 4 * min_overlap, -2 * min_overlap * (h + w)
    c3 = (min_overlap - 1) * w * h
    r3 = (b3 + np.sqrt(max(b3 ** 2 - 4 * a3 * c3, 0.0))) / (2 * a3)
    return max(0.0, min(r1, r2, r3))


def make_centernet_targets(sample: dict, out_size: int = 128,
                           num_classes: int = 80) -> dict:
    """Add 'heatmap' (S,S,C), 'wh' (S,S,2), 'offset' (S,S,2), 'mask' (S,S)
    from normalized x1y1x2y2 'boxes' + 'classes' (padded rows all-zero)."""
    boxes = np.asarray(sample.get("boxes", ()), np.float32).reshape(-1, 4)
    classes = np.asarray(sample.get("classes", ()), np.int32).reshape(-1)
    S = out_size
    hm = np.zeros((S, S, num_classes), np.float32)
    wh = np.zeros((S, S, 2), np.float32)
    off = np.zeros((S, S, 2), np.float32)
    mask = np.zeros((S, S), np.float32)
    for i, b in enumerate(boxes):
        w, h = (b[2] - b[0]) * S, (b[3] - b[1]) * S
        if w <= 0 or h <= 0:
            continue
        cx, cy = (b[0] + b[2]) / 2 * S, (b[1] + b[3]) / 2 * S
        ix, iy = min(int(cx), S - 1), min(int(cy), S - 1)
        r = max(centernet_radius(h, w), 1.0)
        cls = int(classes[i]) if i < len(classes) else 0
        g = gaussian_2d(S, S, cx, cy, r / 3.0)
        hm[:, :, cls] = np.maximum(hm[:, :, cls], g)
        wh[iy, ix] = (w, h)
        off[iy, ix] = (cx - ix, cy - iy)
        mask[iy, ix] = 1.0
    sample["heatmap"] = hm
    sample["wh"] = wh
    sample["offset"] = off
    sample["mask"] = mask
    return sample


class MakePoseHeatmaps:
    def __init__(self, size: int = 64, sigma: float = 1.0, num_joints: int = 16):
        self.kw = dict(size=size, sigma=sigma, num_joints=num_joints)

    def __call__(self, sample: dict, rng) -> dict:
        return make_pose_heatmaps(sample, **self.kw)


class MakeCenternetTargets:
    def __init__(self, out_size: int = 128, num_classes: int = 80):
        self.kw = dict(out_size=out_size, num_classes=num_classes)

    def __call__(self, sample: dict, rng) -> dict:
        return make_centernet_targets(sample, **self.kw)
