"""ReplicaPool: N engine replicas behind one front door.

The fleet layer above serve/router.py's single Server: the failure
modes at "millions of users" scale are replica death, overload
collapse, and weight updates under live traffic — none of which a
single Router can express. A pool owns N replicas, each an in-process
worker thread set owning its own warmed Engine + Server (so the whole
fleet runs on CPU CI; on TPU the same shape maps to one engine per
device, and the `serve.replica` fault kind `crash` maps to the real
process death a multi-host deployment would see).

The request path::

    pool.submit(model, image)
      -> SLOTracker.offered           # every request the front door saw
      -> AdmissionController.admit    # bounded queues + token budget:
                                      #   shed -> typed `serve_shed` +
                                      #   ShedError, no Future created
      -> route: canary x% (swap.py), else least-in-flight SERVING
         replica (the queue-depth/occupancy gauges, as a routing signal)
      -> replica Server.submit        # the PR-6 path, per replica

Replica lifecycle: `warming -> serving -> draining|dead`. Death is
detected two ways — synchronously, when a batch hits the
`serve.replica` fault boundary or a non-request-scoped executor error
(the dispatcher reports fatal before failing its in-flight requests,
so death costs exactly the requests on the dead replica, never the
pool); and asynchronously, when the supervisor notices a serving
replica's dispatcher threads silently gone. Either way the pool
journals a typed `replica_lost`, fails that replica's in-flight
requests request-scoped, and respawns the serving layer over the
SURVIVING warmed engine under a `resilience.RetryPolicy` (typed
`retry` events; `replica_recovered` on success). The engine — the
compiled (model, bucket) executables — is the device-resident artifact
that outlives its frontend, which is why recovery never touches the
compiler (fleet-smoke asserts the counter).
"""
from __future__ import annotations

import queue as _queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional

from deep_vision_tpu.obs import locksmith
from deep_vision_tpu.obs.registry import Registry
from deep_vision_tpu.resilience import faults
from deep_vision_tpu.resilience.retry import RetryPolicy
from deep_vision_tpu.serve.admission import AdmissionController, ShedError
from deep_vision_tpu.serve.engine import Engine, ServeError
from deep_vision_tpu.serve.queue import QueueClosed
from deep_vision_tpu.serve.router import DRAIN_REASONS, Server, ServerClosed
from deep_vision_tpu.serve.slo import SLOTracker

REPLICA_STATES = ("warming", "serving", "draining", "dead")


class ReplicaLost(ServeError):
    """The replica serving this request died; the failure is scoped to
    the requests that were in flight on it — resubmit lands on a
    surviving replica."""


class _ReplicaServer(Server):
    """A Server owned by one pool slot.

    Adds the two fleet behaviors the single-device Server doesn't have:
    the `serve.replica` fault boundary at batch execution (replica death
    is deterministically injectable, like every other failure mode in
    the repo), and fatal-error classification — a request-malformation
    error stays request-scoped exactly as in the base class, while an
    executor-level error below the request layer (or the injected
    replica fault) latches this replica dead and reports to the pool
    BEFORE the base dispatcher fails the in-flight batch.
    """

    #: exception types that are the request's fault, never the replica's
    _REQUEST_SCOPED = (ServeError, ValueError, TypeError)

    def __init__(self, *args, on_fatal: Optional[Callable] = None, **kw):
        super().__init__(*args, **kw)
        self._on_fatal = on_fatal
        self._dead = threading.Event()
        # latches exactly one on_fatal report per replica life even when
        # several model dispatchers hit the boundary at once
        self._fatal_lock = locksmith.lock("serve.replica.fatal")
        self._fatal_reported = False

    @property
    def dead(self) -> bool:
        return self._dead.is_set()

    @property
    def threads_alive(self) -> bool:
        return any(t.is_alive() for t in self._threads)

    def die(self) -> None:
        """Latch dead and close the queues: everything still queued is
        flushed straight into ReplicaLost failures (request-scoped, no
        max-wait lingering) and the dispatchers exit."""
        self._dead.set()
        for q in self._queues.values():
            q.close()

    def _fatal(self, exc: Exception) -> None:
        with self._fatal_lock:
            if self._fatal_reported:
                return
            self._fatal_reported = True
        # report BEFORE closing the queues: the pool marks the slot dead
        # first, so the routing window where a closed-queue replica still
        # looks 'serving' (and would eat a reroute attempt) never opens
        if self._on_fatal is not None:
            self._on_fatal(exc)
        self.die()

    def _run_batch(self, model: str, batch) -> None:
        if self._dead.is_set():
            raise ReplicaLost(
                f"replica {self.tags.get('replica', '?')} is dead; "
                "resubmit to the pool")
        try:
            # the replica execution boundary: an injected serve.replica
            # io_error here IS a replica death (on TPU: the device/runtime
            # erroring out from under the executable)
            faults.fire("serve.replica")
            super()._run_batch(model, batch)
        except self._REQUEST_SCOPED:
            raise  # bad request / contract violation: base class semantics
        except Exception as e:
            self._fatal(e)
            raise ReplicaLost(
                f"replica {self.tags.get('replica', '?')} died mid-batch: "
                f"{type(e).__name__}: {e}") from e


class _Slot:
    """One replica slot: identity, state, and its routing load signal."""

    __slots__ = ("rid", "engine", "server", "state", "inflight", "losses",
                 "canary", "retired")

    def __init__(self, rid: str, engine: Engine, canary: bool = False):
        self.rid = rid
        self.engine = engine
        self.server: Optional[_ReplicaServer] = None
        self.state = "warming"
        self.inflight = 0
        self.losses = 0
        self.canary = canary
        # has this slot's CURRENT server been folded into _retired yet?
        # (a dead server whose respawn gave up must not be retired again
        # at drain — its ledger would double-count in serve_drain)
        self.retired = False


class ReplicaPool:
    """N replicas, one front door: load-aware routing, admission control,
    supervised respawn, canary hosting for serve/swap.py.

    Wire-up (what tools/loadgen.py's fleet smoke does)::

        pool = ReplicaPool(build_engine, replicas=3, journal=journal,
                           admission=AdmissionController(max_queue_depth=32,
                                                         rate_per_s=200),
                           slo_ms=250.0)
        pool.start()                      # warms every replica's engine
        fut = pool.submit("toy", image)   # may raise ShedError
        ...
        pool.drain("close")               # flush everything, aggregate ledger

    `build_engine(replica_id)` returns an UNWARMED Engine with the
    models registered; the pool warms each one and reports the compile
    accounting (replicas x (model, bucket) pairs — warmup is the one
    place the fleet is allowed to compile).
    """

    def __init__(self, build_engine: Callable[[str], Engine],
                 replicas: int = 2, journal=None, registry=None,
                 admission: Optional[AdmissionController] = None,
                 max_wait_ms: float = 5.0, slo_ms: Optional[float] = None,
                 health_policy: str = "warn", drain_timeout_s: float = 30.0,
                 respawn_policy: Optional[RetryPolicy] = None,
                 monitor_interval_s: float = 0.25,
                 respawn_fresh: bool = False, telemetry=None):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.build_engine = build_engine
        self.n_replicas = int(replicas)
        self.journal = journal
        self.registry = registry
        self.admission = admission
        self.max_wait_ms = float(max_wait_ms)
        self.slo_ms = slo_ms
        self.health_policy = health_policy
        self.drain_timeout_s = float(drain_timeout_s)
        self.monitor_interval_s = float(monitor_interval_s)
        # respawn_fresh: rebuild the ENGINE too, not just the server —
        # the fresh-device model, where the dead replica's executables
        # died with its device and there is nothing warm to borrow. The
        # rebuilt engine warms through build_engine's ExecutableCache
        # (when the factory attaches one), so even the nothing-to-borrow
        # respawn performs zero backend compiles — cache-warm AND int8
        # if the factory registers quantized models.
        self.respawn_fresh = bool(respawn_fresh)
        self.respawn_policy = respawn_policy or RetryPolicy(
            name="serve.replica", max_attempts=4, base_delay_s=0.05,
            max_delay_s=1.0, journal=journal,
            retry_on=(OSError, TimeoutError, ServeError))
        self.slo = SLOTracker(registry=registry, slo_ms=slo_ms)
        self._slots: Dict[str, _Slot] = {}
        self._inflight_model: Dict[str, int] = {}
        # the fleet ledger of replaced/removed servers, so drain's
        # accepted == completed + errors + cancelled survives respawns
        self._retired = {"accepted": 0, "completed": 0, "errors": 0,
                         "cancelled": 0}
        self._lock = locksmith.lock("serve.pool")
        self._canary: Optional[_Slot] = None
        self._canary_pct = 0
        self._canary_counter = 0
        self._canary_gen = 0
        self._rr = 0
        self._started = False
        self._draining = False
        self._drained: Optional[dict] = None
        self._drain_done = threading.Event()
        self._respawn_q: _queue.Queue = _queue.Queue()
        self._supervisor: Optional[threading.Thread] = None
        self.warmup_stats: Optional[dict] = None
        # live plane: the pool registers the fleet view; each replica's
        # server registers its own serve:<rid> sources (Server.__init__),
        # and a respawn's fresh server overwrites the dead one's slot
        self.telemetry = telemetry
        if telemetry is not None:
            telemetry.add_health("fleet", self.healthz)
            telemetry.add_status("fleet", self.telemetry_status)

    # -- lifecycle -----------------------------------------------------------

    def _make_server(self, rid: str, engine: Engine,
                     registry=None, health_policy: Optional[str] = None
                     ) -> _ReplicaServer:
        return _ReplicaServer(
            engine, journal=self.journal,
            registry=registry if registry is not None else self.registry,
            max_wait_ms=self.max_wait_ms, slo_ms=self.slo_ms,
            drain_timeout_s=self.drain_timeout_s,
            health_policy=health_policy or self.health_policy,
            tags={"replica": rid}, telemetry=self.telemetry,
            on_fatal=lambda exc, _rid=rid: self._on_replica_fatal(_rid, exc))

    def start(self) -> "ReplicaPool":
        if self._started:
            return self
        per_replica = []
        for i in range(self.n_replicas):
            rid = f"r{i}"
            slot = _Slot(rid, self.build_engine(rid))
            self._slots[rid] = slot
            stats = slot.engine.warmup()
            slot.server = self._make_server(rid, slot.engine)
            slot.server.start()
            slot.state = "serving"
            per_replica.append({"replica": rid, "pairs": stats["pairs"],
                                "backend_compiles": stats["backend_compiles"]})
        self.warmup_stats = {
            "replicas": self.n_replicas,
            "pairs": sum(r["pairs"] for r in per_replica),
            "backend_compiles": sum(r["backend_compiles"]
                                    for r in per_replica),
            "detail": per_replica,
        }
        if self.journal is not None:
            self.journal.write("note", note="pool_warmup", **{
                k: v for k, v in self.warmup_stats.items() if k != "detail"})
        self._supervisor = threading.Thread(
            target=self._supervise, name="pool-supervisor", daemon=True)
        self._supervisor.start()
        self._started = True
        return self

    # -- the front door ------------------------------------------------------

    def submit(self, model: str, image,
               deadline_ms: Optional[float] = None) -> Future:
        """Admit, route, enqueue. Raises ShedError synchronously when
        policy rejects — admission budgets, or the pool draining
        (shutdown is an overload of size infinity: reason `draining`) —
        with no Future created, and ServeError when no serving replica
        remains (counted `refused`, not shed: that is a fleet failure,
        not a policy verdict, and it must not flatter the admitted
        numbers)."""
        if not self._started:
            raise ServeError("submit() before start(): no replicas are up")
        self.slo.offered(model)
        # the admission verdict, the depth it judged, and the in-flight
        # increment commit under ONE pool-lock hold: N racing clients at
        # depth max-1 must admit exactly one, or the queue bound — the
        # latency promise — silently overshoots under exactly the
        # overload it exists for (the admission lock nests inside as a
        # leaf; it never takes the pool lock back)
        with self._lock:
            if self._draining:
                reason: Optional[str] = "draining"
            elif self.admission is not None:
                reason = self.admission.admit(
                    model, self._inflight_model.get(model, 0))
            else:
                reason = None
            slot = None if reason is not None else self._route(model)
        if reason is not None:
            self._shed(model, reason)
        # one reroute, EXCLUDING the replica that just refused: it can
        # die between route and submit — that is the pool's race to
        # absorb, not the client's
        for attempt in range(2):
            if slot is None:
                self.slo.refused(model)
                raise ServeError(
                    f"no serving replicas for {model!r} "
                    f"({self.replica_states()})")
            try:
                fut = slot.server.submit(model, image,
                                         deadline_ms=deadline_ms)
            except QueueClosed:
                self._dec_inflight(slot, model)
                if attempt == 0:
                    with self._lock:
                        slot = self._route(model, exclude=slot)
                    continue  # died/drained under us: reroute once
                break
            except Exception:
                self._dec_inflight(slot, model)
                raise
            fut.add_done_callback(
                lambda _f, _s=slot, _m=model: self._dec_inflight(_s, _m))
            return fut
        self.slo.refused(model)
        raise ServeError(f"no serving replica accepted {model!r}")

    def _shed(self, model: str, reason: str) -> None:
        self.slo.shed(model, reason)
        if self.journal is not None:
            self.journal.write("serve_shed", model=model, reason=reason)
        raise ShedError(model, reason)

    def _route(self, model: str,
               exclude: Optional[_Slot] = None) -> Optional[_Slot]:
        """Pick a replica and commit its in-flight increment. The POOL
        LOCK MUST BE HELD by the caller (submit holds it across the
        admission verdict and this, so verdict and increment are one
        atomic step)."""
        # canary diversion first (serve/swap.py): a deterministic
        # pct% of the stream, evenly spread, so a seeded arrival
        # pattern reproduces the exact same canary sample
        canary = self._canary
        if (canary is not None and canary.state == "serving"
                and canary is not exclude and self._canary_pct > 0):
            self._canary_counter += 1
            i, pct = self._canary_counter, self._canary_pct
            if (i * pct) // 100 > ((i - 1) * pct) // 100:
                return self._take(canary, model)
        serving = [s for s in self._slots.values()
                   if s.state == "serving" and not s.canary
                   and s is not exclude]
        if not serving:
            return None
        self._rr += 1
        slot = min(serving,
                   key=lambda s: (s.inflight,
                                  (hash(s.rid) + self._rr)
                                  % max(1, len(serving))))
        return self._take(slot, model)

    def _take(self, slot: _Slot, model: str) -> _Slot:
        slot.inflight += 1
        self._inflight_model[model] = self._inflight_model.get(model, 0) + 1
        self.slo.replica_queue_depth(slot.rid, slot.inflight)
        return slot

    def _dec_inflight(self, slot: _Slot, model: str) -> None:
        with self._lock:
            slot.inflight = max(0, slot.inflight - 1)
            self._inflight_model[model] = max(
                0, self._inflight_model.get(model, 0) - 1)
            self.slo.replica_queue_depth(slot.rid, slot.inflight)

    # -- replica death + respawn ---------------------------------------------

    def _on_replica_fatal(self, rid: str, exc: Exception) -> None:
        """Called (once per replica life) from the dying replica's
        dispatcher thread, before its queues close and before its
        in-flight batch is failed — routing stops here, first."""
        with self._lock:
            slot = self._slots.get(rid)
            if slot is None or slot.state == "dead":
                return
            slot.state = "dead"
            slot.losses += 1
            losses = slot.losses
            is_canary = slot.canary
        self.slo.registry.counter(
            "serve_replica_lost_total", "replica deaths",
            labels={"replica": rid}).inc()
        if self.journal is not None:
            self.journal.write(
                "replica_lost", replica=rid, attempt=int(losses),
                error=f"{type(exc).__name__}: {exc}"[:200])
        if not is_canary:
            # canary replicas are the swap controller's to bury: their
            # death IS the canary verdict, not a slot to respawn
            self._respawn_q.put(rid)

    def _supervise(self) -> None:
        """Respawn worker + liveness monitor. A dead replica arrives on
        the queue (synchronous detection); the timeout doubles as the
        poll for replicas whose dispatchers died without reporting."""
        while True:
            try:
                rid = self._respawn_q.get(timeout=self.monitor_interval_s)
            except _queue.Empty:
                self._check_liveness()
                continue
            if rid is None:
                return
            self._respawn(rid)

    def _check_liveness(self) -> None:
        with self._lock:
            suspects = [s for s in self._slots.values()
                        if s.state == "serving" and s.server is not None
                        and not s.server.threads_alive]
        for slot in suspects:
            # route through the same fatal path so detection source
            # doesn't change the journal/respawn story
            slot.server._fatal(ReplicaLost(
                f"replica {slot.rid} dispatcher threads died silently"))

    def _retire(self, slot: _Slot) -> None:
        """Fold a replaced/removed server's ledger into the pool totals,
        once (its threads must be done: counts are final)."""
        with self._lock:
            if slot.retired or slot.server is None:
                return
            slot.retired = True
            server = slot.server
        for t in server._threads:
            t.join(timeout=self.drain_timeout_s)
        counts = server.counts()
        with self._lock:
            for k in self._retired:
                self._retired[k] += counts[k]

    def _respawn(self, rid: str) -> None:
        with self._lock:
            slot = self._slots.get(rid)
            if slot is None or slot.state != "dead":
                return
            engine = slot.engine
        self._retire(slot)
        attempts = {"n": 0}
        fresh = {"engine": None}

        def build() -> _ReplicaServer:
            attempts["n"] += 1
            # respawn rides the same injection point as death: a
            # serve.replica io_error here is a failed respawn attempt
            # the RetryPolicy backs off and retries
            faults.fire("serve.replica")
            server_engine = engine
            if self.respawn_fresh:
                # fresh-device respawn: nothing survives to borrow, so
                # the engine rebuilds and re-warms — through the
                # factory's ExecutableCache when one is attached, which
                # is what keeps this path off the compiler
                server_engine = self.build_engine(rid)
                stats = server_engine.warmup()
                fresh["engine"] = server_engine
                if self.journal is not None:
                    self.journal.write(
                        "note", note="replica_respawn_fresh", replica=rid,
                        pairs=stats["pairs"],
                        backend_compiles=stats["backend_compiles"],
                        cache_hits=stats.get("cache_hits", 0))
            server = self._make_server(rid, server_engine)
            server.start()
            return server

        try:
            server = self.respawn_policy.call(build)
        except Exception as e:  # budget spent: slot stays dead, pool serves on
            if self.journal is not None:
                self.journal.write(
                    "note", note="replica_respawn_gave_up", replica=rid,
                    error=f"{type(e).__name__}: {e}"[:200])
            return
        with self._lock:
            if fresh["engine"] is not None:
                slot.engine = fresh["engine"]
            slot.server = server
            slot.inflight = 0
            slot.retired = False  # a fresh ledger to fold in later
            slot.state = "serving"
        self.slo.registry.counter(
            "serve_replica_recovered_total", "replica respawns",
            labels={"replica": rid}).inc()
        if self.journal is not None:
            self.journal.write("replica_recovered", replica=rid,
                               attempt=int(attempts["n"]))

    # -- canary hosting (serve/swap.py) --------------------------------------

    def primary_engine(self) -> Engine:
        """The engine whose executables a swap's shadow will share."""
        with self._lock:
            for slot in self._slots.values():
                if slot.state == "serving" and not slot.canary:
                    return slot.engine
        raise ServeError("no serving replica to anchor a swap on")

    def add_canary(self, engine: Engine, pct: int) -> str:
        """Mount a canary replica over `engine` taking `pct`% of traffic.
        The canary always runs health_policy=abort — its entire job is
        turning bad weights into request errors the verdict can count —
        and gets a private metrics registry so its latency tail judges
        only canary traffic."""
        if not 0 < pct <= 100:
            raise ValueError(f"canary pct must be in (0, 100], got {pct}")
        with self._lock:
            if self._canary is not None:
                raise ServeError("a canary replica is already mounted")
            self._canary_gen += 1
            rid = f"canary{self._canary_gen}"
        server = self._make_server(rid, engine, registry=Registry(),
                                   health_policy="abort")
        server.start()
        with self._lock:
            slot = _Slot(rid, engine, canary=True)
            slot.server = server
            slot.state = "serving"
            self._slots[rid] = slot
            self._canary = slot
            self._canary_pct = int(pct)
            self._canary_counter = 0
        return rid

    def canary_status(self) -> Optional[dict]:
        with self._lock:
            slot = self._canary
        if slot is None:
            return None
        counts = slot.server.counts()
        return {"replica": slot.rid, "state": slot.state, **counts,
                "slo": slot.server.slo.report()}

    def remove_canary(self) -> Optional[dict]:
        """Unmount the canary (promote or rollback: either way the
        diverted traffic returns to the base replicas) and retire its
        ledger. Returns its drain summary, or None without a canary."""
        with self._lock:
            slot = self._canary
            self._canary = None
            self._canary_pct = 0
        if slot is None:
            return None
        with self._lock:
            slot.state = "draining"
        summary = slot.server.drain("close")
        self._retire(slot)
        with self._lock:
            self._slots.pop(slot.rid, None)
        return summary

    def promote_variables(self, variables_by_model: dict) -> None:
        """Hot-swap the new weights into every base replica's engine
        (dead slots included: their engine survives and a respawn must
        come back serving the promoted weights). Zero-downtime: each
        engine swap is one validated attribute assignment that takes
        effect at that replica's next batch."""
        with self._lock:
            engines = [s.engine for s in self._slots.values()
                       if not s.canary]
        for engine in engines:
            for name, variables in variables_by_model.items():
                engine.set_variables(name, variables)

    # -- drain / report ------------------------------------------------------

    def replica_states(self) -> Dict[str, str]:
        with self._lock:
            return {rid: s.state for rid, s in self._slots.items()}

    def healthz(self):
        """Telemetry health source: the fleet is ready while at least one
        replica serves and the pool is not draining — a dead replica
        mid-respawn degrades capacity, not readiness."""
        states = self.replica_states()
        with self._lock:
            draining = self._draining or self._drained is not None
        serving = sum(1 for s in states.values() if s == "serving")
        ok = self._started and not draining and serving > 0
        return ok, {"started": self._started, "draining": draining,
                    "serving": serving, "replicas": len(states),
                    "states": states}

    def telemetry_status(self) -> dict:
        """Telemetry status source: replica states + fleet ledger +
        canary generation for /statusz."""
        with self._lock:
            replicas = {rid: {"state": s.state, "inflight": s.inflight,
                              "losses": s.losses, "canary": s.canary}
                        for rid, s in self._slots.items()}
            retired = dict(self._retired)
            generation = self._canary_gen
            canary_pct = self._canary_pct
        return {"replicas": replicas, "retired": retired,
                "generation": generation, "canary_pct": canary_pct,
                "warmup": self.warmup_stats}

    def drain(self, reason: str = "close") -> dict:
        """Flush every admitted request, stop every replica, aggregate
        the fleet ledger into one `serve_drain` (written after the
        per-replica ones, so the journal's last drain verdict is the
        pool's). Idempotent."""
        if reason not in DRAIN_REASONS:
            raise ValueError(f"drain reason {reason!r} not in {DRAIN_REASONS}")
        with self._lock:
            already = self._drained is not None
            if not already:
                # full-keyed placeholder (the Server.drain shape): a
                # concurrent caller that times out waiting below still
                # sees a well-formed summary, and only ONE caller ever
                # runs the body — a SIGTERM drain racing a clean close
                # must not journal two fleet verdicts or dump a preempt
                # bundle after the close already finished
                self._drained = {
                    "reason": reason, "outcome": "timeout", "accepted": 0,
                    "completed": 0, "errors": 0, "cancelled": 0,
                    "pending": 0, "shed": 0, "offered": 0, "refused": 0,
                    "replicas": 0,
                }
                self._draining = True
            slots = list(self._slots.values())
        if already:
            self._drain_done.wait(timeout=self.drain_timeout_s)
            with self._lock:
                return self._drained
        try:
            if self.admission is not None:
                self.admission.start_draining()
            self._respawn_q.put(None)
            if self._supervisor is not None:
                self._supervisor.join(timeout=self.drain_timeout_s)
            summaries = {}
            for slot in slots:
                if slot.state == "dead":
                    self._retire(slot)  # no-op if its give-up already did
                    continue
                with self._lock:
                    slot.state = "draining"
                # replicas always drain with reason `close`: the pool
                # owns the preemption semantics (ONE preempt bundle
                # below, not N)
                summaries[slot.rid] = slot.server.drain("close")
            with self._lock:
                totals = dict(self._retired)
            for s in summaries.values():
                for k in totals:
                    totals[k] += s.get(k, 0)
            pending = (totals["accepted"] - totals["completed"]
                       - totals["errors"] - totals["cancelled"])
            outcome = ("flushed"
                       if pending == 0 and all(s["outcome"] == "flushed"
                                               for s in summaries.values())
                       else "timeout")
            slo_report = self.slo.report().values()
            summary = {"reason": reason, "outcome": outcome, **totals,
                       "pending": max(0, pending),
                       "shed": sum(r.get("shed", 0) for r in slo_report),
                       "offered": sum(r.get("offered", 0)
                                      for r in slo_report),
                       "refused": sum(r.get("refused", 0)
                                      for r in slo_report),
                       "replicas": len(summaries)}
            if self.journal is not None:
                self.journal.write("serve_drain", scope="pool", **summary)
            if reason == "sigterm":
                from deep_vision_tpu.obs import flight

                summary["flight_bundle"] = flight.emergency_dump("preempt")
            with self._lock:
                self._drained = summary
            return summary
        finally:
            self._drain_done.set()

    def close(self) -> dict:
        return self.drain("close")

    def report(self) -> dict:
        with self._lock:
            replicas = {rid: {"state": s.state, "inflight": s.inflight,
                              "losses": s.losses, "canary": s.canary}
                        for rid, s in self._slots.items()}
        return {"replicas": replicas, "slo": self.slo.report(),
                "drained": self._drained}
