"""CLI: `python train.py -m <config> [-c <ckpt>]` — the reference's entry
contract (argparse mains at ResNet/pytorch/train.py:541-562, resume-by-flag
at :293-307) over the shared config registry.

`--fake-data` swaps in synthetic datasets of the exact task shapes — the
fleshed-out version of the CPU fake-data harness the reference kept commented
out (CycleGAN/tensorflow/train.py:338-342) — so every config trains end to
end on any host, TPU or CPU.
"""
from __future__ import annotations

import argparse
import os
import sys as _sys
from typing import List, Optional

import numpy as np

from deep_vision_tpu.configs import CONFIG_REGISTRY, ExperimentConfig, get_config


def model_input_shape(cfg: ExperimentConfig):
    """The shape the MODEL consumes: cfg.input_shape after any host-side
    layout transform (stem='s2d' ships (H/2, W/2, 4C), models/resnet.py)."""
    h, w, c = cfg.input_shape
    if cfg.model_kwargs.get("stem") == "s2d":
        return (h // 2, w // 2, 4 * c)
    return cfg.input_shape


# -- fake datasets -----------------------------------------------------------

def _fake_classification(cfg: ExperimentConfig, n_batches: int):
    rng = np.random.RandomState(0)
    h, w, c = model_input_shape(cfg)
    return [
        {
            "image": rng.rand(cfg.batch_size, h, w, c).astype(np.float32),
            "label": rng.randint(0, cfg.num_classes, (cfg.batch_size,)).astype(np.int32),
        }
        for _ in range(n_batches)
    ]


def _fake_detection(cfg: ExperimentConfig, n_batches: int, max_boxes=20):
    rng = np.random.RandomState(0)
    h, w, c = cfg.input_shape
    out = []
    for _ in range(n_batches):
        boxes = np.zeros((cfg.batch_size, max_boxes, 4), np.float32)
        classes = np.zeros((cfg.batch_size, max_boxes), np.int32)
        for b in range(cfg.batch_size):
            n = rng.randint(1, 5)
            x1 = rng.uniform(0, 0.6, n)
            y1 = rng.uniform(0, 0.6, n)
            boxes[b, :n, 0], boxes[b, :n, 1] = x1, y1
            boxes[b, :n, 2] = x1 + rng.uniform(0.1, 0.35, n)
            boxes[b, :n, 3] = y1 + rng.uniform(0.1, 0.35, n)
            classes[b, :n] = rng.randint(0, cfg.num_classes, n)
        out.append(
            {
                "image": rng.rand(cfg.batch_size, h, w, c).astype(np.float32),
                "boxes": boxes,
                "classes": classes,
            }
        )
    return out


def _fake_pose(cfg: ExperimentConfig, n_batches: int, hm_size=64):
    from deep_vision_tpu.data.labels import make_pose_heatmaps

    rng = np.random.RandomState(0)
    h, w, c = cfg.input_shape
    out = []
    for _ in range(n_batches):
        hms, kps, viss = [], [], []
        for _b in range(cfg.batch_size):
            s = {
                "keypoints": rng.rand(cfg.num_classes, 2).astype(np.float32),
                "visibility": np.ones((cfg.num_classes,), np.float32),
            }
            hms.append(make_pose_heatmaps(s, size=hm_size,
                                          num_joints=cfg.num_classes)["heatmap"])
            kps.append(s["keypoints"])
            viss.append(s["visibility"])
        out.append(
            {
                "image": rng.rand(cfg.batch_size, h, w, c).astype(np.float32),
                "heatmap": np.stack(hms),
                "keypoints": np.stack(kps),
                "visibility": np.stack(viss),
            }
        )
    return out


def _fake_centernet(cfg: ExperimentConfig, n_batches: int):
    from deep_vision_tpu.data.labels import make_centernet_targets

    det = _fake_detection(cfg, n_batches)
    out_size = cfg.input_shape[0] // 4
    out = []
    for batch in det:
        tgts = [
            make_centernet_targets(
                {"boxes": batch["boxes"][b], "classes": batch["classes"][b]},
                out_size=out_size, num_classes=cfg.num_classes,
            )
            for b in range(len(batch["image"]))
        ]
        out.append(
            {
                "image": batch["image"],
                # raw boxes ride along like the real pipeline's (PadBoxes
                # stays in the sample dict) — --eval-only mAP needs them
                "boxes": batch["boxes"],
                "classes": batch["classes"],
                "heatmap": np.stack([t["heatmap"] for t in tgts]),
                "wh": np.stack([t["wh"] for t in tgts]),
                "offset": np.stack([t["offset"] for t in tgts]),
                "mask": np.stack([t["mask"] for t in tgts]),
            }
        )
    return out


# -- real datasets -----------------------------------------------------------

def build_dataloaders(cfg: ExperimentConfig, data_dir: str, fake: bool,
                      fake_batches: int, num_workers: int,
                      preprocessing: str = "torch", num_procs: int = 0,
                      bad_record_budget=None, host_shard=None):
    """Returns (train_fn, eval_fn) thunks yielding batch dicts per epoch.

    `preprocessing` selects the ImageNet normalization chain: "torch" is the
    torchvision-stats chain (ResNet/pytorch/train.py:315-331); "tf" is the
    TF "ResNet preprocessing" 0-255 mean-subtraction variant
    (ResNet/tensorflow/data_load.py:158-193).

    `bad_record_budget` (records.BadRecordBudget) applies only to the
    record-backed kinds: corrupt/undecodable records are skipped and
    dead-lettered under its bound instead of killing the epoch. One budget
    object is shared by the train and eval datasets — the bound is per
    run, not per split.

    `host_shard` ((shard_index, num_shards), i.e. `multihost.host_shard()`)
    feeds per-host sharded loading on the record-backed TRAIN loaders:
    each host reads only its disjoint shard slice, and because the value
    comes from the CURRENT rendezvous generation, an elastic 3->2 resize
    re-derives the slices for free (resilience/rendezvous.py). Eval
    loaders stay unsharded — every host evaluates the full split.
    """
    if fake or cfg.dataset.get("kind") == "fake":
        maker = {
            "classification": _fake_classification,
            "detection": _fake_detection,
            "pose": _fake_pose,
            "centernet": _fake_centernet,
            "dcgan": _fake_classification,
            "cyclegan": _fake_classification,
        }[cfg.task]
        data = maker(cfg, fake_batches)
        return (lambda: data), (lambda: data)

    from deep_vision_tpu.data import DataLoader, Compose, MnistDataset, RecordDataset
    from deep_vision_tpu.data import transforms as T
    from deep_vision_tpu.data.datasets import ImageFolderDataset
    from deep_vision_tpu.data.labels import MakeCenternetTargets, MakePoseHeatmaps

    kind = cfg.dataset["kind"]
    if kind == "mnist":
        train_ds = MnistDataset(
            os.path.join(data_dir, "train-images-idx3-ubyte"),
            os.path.join(data_dir, "train-labels-idx1-ubyte"),
        )
        eval_ds = MnistDataset(
            os.path.join(data_dir, "t10k-images-idx3-ubyte"),
            os.path.join(data_dir, "t10k-labels-idx1-ubyte"),
        )
        tf_ = Compose([T.ToFloat(), T.Normalize(mean=[0.1307], std=[0.3081])])
        train = DataLoader(train_ds, cfg.batch_size, tf_, shuffle=True,
                           num_workers=num_workers, name="train")
        evl = DataLoader(eval_ds, cfg.batch_size, tf_, num_workers=num_workers,
                         name="val")
        return (lambda: train), (lambda: evl)

    if kind == "imagenet":
        # records if present, else flattened folder (data_load.py:14-69)
        rec_glob = os.path.join(data_dir, "tfrecord_train", "*")
        import glob as _g

        if preprocessing == "tf":
            # TF chain: aspect resize -> crop -> flip -> 0-255 mean-sub, no
            # rescaling (preprocess_image, ResNet/tensorflow/data_load.py:158-193)
            train_tf = Compose([
                T.Rescale(cfg.train_resize), T.RandomHorizontalFlip(),
                T.RandomCrop(cfg.eval_crop),
                T.ToFloat(expand_gray_to_rgb=True, scale=False),
                T.MeanSubtract(),
            ])
            eval_tf = Compose([
                T.Rescale(cfg.train_resize), T.CenterCrop(cfg.eval_crop),
                T.ToFloat(expand_gray_to_rgb=True, scale=False),
                T.MeanSubtract(),
            ])
        else:
            train_tf = Compose([
                T.Rescale(cfg.train_resize), T.RandomHorizontalFlip(),
                T.RandomCrop(cfg.eval_crop),
                T.ColorJitter(0.4, 0.4, 0.4),
                T.ToFloatNormalize(expand_gray_to_rgb=True),
            ])  # transforms.Compose at ResNet/pytorch/train.py:315-331
            eval_tf = Compose([
                T.Rescale(cfg.train_resize), T.CenterCrop(cfg.eval_crop),
                T.ToFloatNormalize(expand_gray_to_rgb=True),
            ])
        if cfg.model_kwargs.get("stem") == "s2d":
            # host half of the MLPerf stem trick (models/resnet.py
            # SpaceToDepthStem): lay images out (H/2, W/2, 12) on the host
            train_tf = Compose([train_tf, T.SpaceToDepth()])
            eval_tf = Compose([eval_tf, T.SpaceToDepth()])
        if _g.glob(rec_glob):
            train_ds = RecordDataset(rec_glob, "imagenet", shuffle_shards=True,
                                     bad_record_budget=bad_record_budget)
            eval_ds = RecordDataset(
                os.path.join(data_dir, "tfrecord_val", "*"), "imagenet",
                bad_record_budget=bad_record_budget,
            )
            train = DataLoader(train_ds, cfg.batch_size, train_tf, shuffle=True,
                               shuffle_buffer=10000, num_workers=num_workers,
                               num_procs=num_procs, name="train",
                               host_shard=host_shard)
        else:
            train_ds = ImageFolderDataset(os.path.join(data_dir, "train_flatten"))
            eval_ds = ImageFolderDataset(os.path.join(data_dir, "val_flatten"))
            # forwarding num_procs surfaces the folder dataset's lack of
            # .split as a clear TypeError instead of silently ignoring it
            train = DataLoader(train_ds, cfg.batch_size, train_tf, shuffle=True,
                               num_workers=num_workers, num_procs=num_procs,
                               name="train")
        evl = DataLoader(eval_ds, cfg.batch_size, eval_tf, num_workers=num_workers,
                         name="val")
        return (lambda: train), (lambda: evl)

    if kind == "records":
        schema = cfg.dataset["schema"]
        size = cfg.input_shape[0]
        # eval chains carry no random augments (the imagenet split above does
        # the same): plateau schedules key on val metrics, which must be
        # deterministic for a fixed checkpoint
        if cfg.task == "detection":
            train_chain = [T.RandomHorizontalFlip(), T.RandomCropWithBoxes(),
                           T.Resize(size), T.ToFloat(), T.PadBoxes(100)]
            eval_chain = [T.Resize(size), T.ToFloat(), T.PadBoxes(100)]
        elif cfg.task == "pose":
            # keypoint-driven person crop + the reference's scale
            # augmentation (random margin, preprocess.py:18-20) + the
            # CORRECTED left/right-swapping flip its disabled version lacked
            train_chain = [T.CropRoi(margin=(0.1, 0.3)),
                           T.RandomHorizontalFlip(
                               keypoint_swap_pairs=T.MPII_FLIP_PAIRS),
                           T.Resize(size), T.ToFloat(),
                           MakePoseHeatmaps(num_joints=cfg.num_classes)]
            eval_chain = [T.CropRoi(margin=0.2),  # fixed margin, as eval
                          T.Resize(size), T.ToFloat(),
                          MakePoseHeatmaps(num_joints=cfg.num_classes)]
        elif cfg.task == "centernet":
            targets = MakeCenternetTargets(size // 4, cfg.num_classes)
            train_chain = [T.RandomHorizontalFlip(), T.Resize(size),
                           T.ToFloat(), T.PadBoxes(100), targets]
            eval_chain = [T.Resize(size), T.ToFloat(), T.PadBoxes(100), targets]
        else:  # image_only (GANs): scale to [-1, 1]
            train_chain = [T.Resize(size), T.ToFloat(),
                           T.Normalize(mean=[0.5] * cfg.input_shape[2],
                                       std=[0.5] * cfg.input_shape[2])]
            eval_chain = train_chain
        train_ds = RecordDataset(
            os.path.join(data_dir, cfg.dataset.get("train_glob", "train*")),
            schema, shuffle_shards=True,
            bad_record_budget=bad_record_budget,
        )
        eval_ds = RecordDataset(
            os.path.join(data_dir, cfg.dataset.get("val_glob", "val*")), schema,
            bad_record_budget=bad_record_budget,
        )
        train = DataLoader(train_ds, cfg.batch_size, Compose(train_chain),
                           shuffle=True, num_workers=num_workers,
                           num_procs=num_procs, drop_remainder=True,
                           name="train", host_shard=host_shard)
        evl = DataLoader(eval_ds, cfg.batch_size, Compose(eval_chain),
                         num_workers=num_workers, drop_remainder=True,
                         name="val")
        return (lambda: train), (lambda: evl)

    raise ValueError(f"unknown dataset kind {kind!r}")


# -- trainer assembly --------------------------------------------------------

def _steps_per_epoch(cfg: ExperimentConfig, train_fn) -> int:
    data = train_fn()
    try:
        return len(data)
    except TypeError:
        return 1000  # streaming: nominal epoch length


def _build_schedule(cfg: ExperimentConfig, steps_per_epoch: int):
    from deep_vision_tpu.train.optimizers import make_schedule

    base_lr = cfg.optimizer["learning_rate"]
    if cfg.schedule is None:
        return base_lr
    kw = dict(cfg.schedule)
    kind = kw.pop("kind")
    if "step_size_epochs" in kw:
        kw["step_size"] = kw.pop("step_size_epochs") * steps_per_epoch
    if "total_epochs" in kw:
        kw["total_steps"] = kw.pop("total_epochs") * steps_per_epoch
    if "hold_epochs" in kw:
        kw["hold_steps"] = kw.pop("hold_epochs") * steps_per_epoch
    if "warmup_epochs" in kw:
        kw["warmup_steps"] = kw.pop("warmup_epochs") * steps_per_epoch
    return make_schedule(kind, base_lr, **kw)


def build_trainer(cfg: ExperimentConfig, train_fn, ckpt_dir: Optional[str],
                  tb_dir: Optional[str] = None,
                  profile_dir: Optional[str] = None,
                  checkify_errors: bool = False,
                  ema_decay: Optional[float] = None,
                  journal=None,
                  telemetry_sample_every: int = 16,
                  health=None,
                  autoprof=None,
                  multistep: int = 1,
                  device_prefetch: int = 0,
                  opt_state_dtype: Optional[str] = None,
                  backend_supervisor=None,
                  data_loader=None,
                  steps_per_epoch: Optional[int] = None,
                  executable_cache=None,
                  sharding_rules=None,
                  telemetry=None):
    import functools

    import jax.numpy as jnp

    from deep_vision_tpu.core import CheckpointManager
    from deep_vision_tpu.losses import (
        centernet_loss_fn,
        classification_loss_fn,
        hourglass_loss_fn,
        yolo_train_loss_fn,
    )
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train import Trainer, build_optimizer
    from deep_vision_tpu.train.optimizers import ReduceLROnPlateau

    # a --data-service stream has no len(): the caller passes its epoch
    # window so LR schedules are built for the steps that actually run
    # (the streaming fallback of 1000 would stretch a cosine ~16x)
    steps = (steps_per_epoch if steps_per_epoch is not None
             else _steps_per_epoch(cfg, train_fn))
    opt_kw = dict(cfg.optimizer)
    name = opt_kw.pop("name")
    opt_kw.pop("learning_rate")
    lr = _build_schedule(cfg, steps)
    wd = opt_kw.pop("weight_decay", 0.0)
    tx = build_optimizer(name, lr, weight_decay=wd, decay_bn_bias=True,
                         state_dtype=opt_state_dtype, **opt_kw)

    if cfg.task == "classification":
        model = get_model(cfg.model, num_classes=cfg.num_classes, **cfg.model_kwargs)
        loss_fn = functools.partial(classification_loss_fn, **cfg.loss_kwargs)
        plateau_metric = cfg.plateau_metric
    elif cfg.task == "detection":
        model = get_model(cfg.model, num_classes=cfg.num_classes, **cfg.model_kwargs)
        size = cfg.input_shape[0]
        loss_fn = functools.partial(
            yolo_train_loss_fn,
            grid_sizes=(size // 32, size // 16, size // 8),
            num_classes=cfg.num_classes, **cfg.loss_kwargs,
        )
        plateau_metric = cfg.plateau_metric
    elif cfg.task == "pose":
        model = get_model(cfg.model, **cfg.model_kwargs)
        loss_fn = functools.partial(hourglass_loss_fn, **cfg.loss_kwargs)
        plateau_metric = cfg.plateau_metric
    elif cfg.task == "centernet":
        model = get_model(cfg.model, num_classes=cfg.num_classes, **cfg.model_kwargs)
        loss_fn = functools.partial(centernet_loss_fn, **cfg.loss_kwargs)
        plateau_metric = cfg.plateau_metric
    else:
        raise ValueError(f"task {cfg.task!r} uses a GAN trainer, not Trainer")

    plateau = ReduceLROnPlateau(**cfg.plateau) if cfg.plateau else None
    # journal-wired: quarantines and sidecar retries become typed events
    ckpt = CheckpointManager(ckpt_dir, journal=journal) if ckpt_dir else None
    sample = jnp.ones((2, *model_input_shape(cfg)), jnp.float32)
    from deep_vision_tpu.core.metrics import MetricLogger
    from deep_vision_tpu.obs.registry import get_registry

    tb = None
    if tb_dir:
        from deep_vision_tpu.core.tensorboard import SummaryWriter

        tb = SummaryWriter(tb_dir)
    # loggers always carry the registry (and the train logger the journal):
    # stdout/TensorBoard/Prometheus/JSONL all fan out from one log call.
    # The val logger stays journal-free — Trainer.evaluate writes the typed
    # 'eval' event, a journal-wired val logger would duplicate it.
    logger = MetricLogger(tb_writer=tb, name="train",
                          registry=get_registry(), journal=journal)
    eval_logger = MetricLogger(tb_writer=tb, name="val", print_every=0,
                               registry=get_registry())
    return Trainer(
        model, tx, loss_fn, sample, plateau=plateau,
        plateau_metric=plateau_metric, checkpoint_manager=ckpt,
        logger=logger, eval_logger=eval_logger, profile_dir=profile_dir,
        checkify_errors=checkify_errors, ema_decay=ema_decay,
        journal=journal, lr_schedule=lr,
        telemetry_sample_every=telemetry_sample_every,
        health=health, autoprof=autoprof,
        multistep=multistep, device_prefetch=device_prefetch,
        backend_supervisor=backend_supervisor,
        data_loader=data_loader,
        executable_cache=executable_cache,
        sharding_rules=sharding_rules,
        telemetry=telemetry,
    )


def build_gan_trainer(cfg: ExperimentConfig, journal=None,
                      telemetry_sample_every: int = 32, health=None,
                      autoprof=None):
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train import build_optimizer
    from deep_vision_tpu.train.gan import CycleGanTrainer, DcganTrainer

    opt_kw = dict(cfg.optimizer)
    name = opt_kw.pop("name")
    lr = opt_kw.pop("learning_rate")
    if cfg.task == "dcgan":
        return DcganTrainer(
            get_model("dcgan_generator"),
            get_model("dcgan_discriminator"),
            build_optimizer(name, lr, **opt_kw),
            build_optimizer(name, lr, **opt_kw),
            image_shape=cfg.input_shape,
            journal=journal,
            telemetry_sample_every=telemetry_sample_every,
            health=health,
            autoprof=autoprof,
        )
    tx_fn = lambda: build_optimizer(name, lr, **dict(opt_kw))
    return CycleGanTrainer(
        get_model("cyclegan_generator"), get_model("cyclegan_generator"),
        get_model("cyclegan_discriminator"), get_model("cyclegan_discriminator"),
        tx_fn, tx_fn, image_shape=cfg.input_shape,
        journal=journal,
        telemetry_sample_every=telemetry_sample_every,
        health=health,
        autoprof=autoprof,
    )


def run_eval_only(cfg: ExperimentConfig, trainer, eval_fn) -> dict:
    """Quality evaluation from a checkpoint: the reference's demo-notebook
    role (YOLO demo_mscoco.ipynb, Hourglass demo_hourglass_pose.ipynb) as a
    CLI mode, with the metrics the reference never shipped (mAP 'working in
    progress' at YOLO/tensorflow/README.md:28-31; no PCK anywhere)."""
    import jax

    variables = {"params": trainer.state.params}
    if trainer.state.batch_stats:
        variables["batch_stats"] = trainer.state.batch_stats

    if cfg.task == "classification":
        summary = trainer.evaluate(eval_fn())
        print("eval: " + " ".join(f"{k}={v:.4f}" for k, v in summary.items()))
        return summary

    if cfg.task in ("detection", "centernet"):
        from deep_vision_tpu.core.detection_metrics import DetectionEvaluator
        from deep_vision_tpu.inference import (
            make_centernet_detector,
            make_yolo_detector,
        )

        if cfg.task == "detection":
            detect = make_yolo_detector(trainer.model, score_threshold=0.1)
        else:
            detect = make_centernet_detector(trainer.model)
        ev = DetectionEvaluator(cfg.num_classes)
        for batch in eval_fn():
            out = jax.device_get(detect(variables, batch["image"]))
            for i in range(len(batch["image"])):
                ev.add(out["boxes"][i], out["scores"][i], out["classes"][i],
                       batch["boxes"][i], batch["classes"][i])
        res = ev.compute(iou_threshold=0.5)
        coco = ev.compute_coco()
        print(f"eval: mAP@.5={res['mAP']:.4f} "
              f"mAP@[.5:.95]={coco['mAP@[.5:.95]']:.4f} "
              f"images={res['num_images']}")
        return {"mAP@.5": res["mAP"], **coco}

    if cfg.task == "pose":
        from deep_vision_tpu.core.detection_metrics import pck
        from deep_vision_tpu.inference import make_pose_estimator

        estimate = make_pose_estimator(trainer.model)
        preds, gts, viss, norms = [], [], [], []
        head_flags = set()
        for batch in eval_fn():
            kpts = np.asarray(jax.device_get(estimate(variables, batch["image"])))
            preds.append(kpts[..., :2])
            gts.append(np.asarray(batch["keypoints"]))
            viss.append(np.asarray(
                batch.get("visibility", np.ones(kpts.shape[:2]))) > 0)
            # PCKh when the records carry a head size; else image-normalized
            # PCK@0.05 (coordinates are in [0,1], so norm=1 is the image side)
            head_flags.add("head_size" in batch)
            norms.append(np.asarray(
                batch.get("head_size", np.ones(len(kpts)))))
        if len(head_flags) > 1:
            raise ValueError(
                "eval batches are inconsistent: some carry 'head_size', some "
                "don't — PCKh and image-normalized PCK cannot be mixed"
            )
        alpha = 0.5 if head_flags == {True} else 0.05
        out = pck(np.concatenate(preds), np.concatenate(gts),
                  np.concatenate(viss), np.concatenate(norms), alpha=alpha)
        key = [k for k in out if k.startswith("PCK")][0]
        print(f"eval: {key}={out[key]:.4f} visible={out['num_visible']}")
        return out

    raise ValueError(f"--eval-only unsupported for task {cfg.task!r}")


def _maybe_upload(args, ckpt_dir: str) -> None:
    if not args.upload_to:
        return
    from deep_vision_tpu.tools.cloud import upload_artifact

    uri = upload_artifact(ckpt_dir, args.upload_to)
    print(f"uploaded checkpoints to {uri}")


def _make_journal(args, cfg: ExperimentConfig, budget=None):
    from deep_vision_tpu.obs import locksmith

    if not args.journal:
        # DVT_LOCKSMITH arms the runtime lock sanitizer even journal-less
        # (violations still count in the registry and report())
        locksmith.arm_from_env()
        return None
    import dataclasses

    from deep_vision_tpu.obs import RunJournal

    journal = RunJournal(args.journal, kind="train")
    # chaos-smoke children run with DVT_LOCKSMITH=1: lock-order
    # violations and hold-time outliers land as typed journal events
    locksmith.arm_from_env(journal=journal)
    journal.manifest(config=dataclasses.asdict(cfg))
    # late-attach the resilience emitters (both are built before the
    # journal exists): injected faults and skipped records then show up
    # as typed `fault`/`data_skip` events next to the steps they hit
    from deep_vision_tpu.resilience import installed

    inj = installed()
    if inj is not None:
        inj.set_journal(journal)
    if budget is not None:
        budget.journal = journal
    return journal


def _make_tracer(args, journal):
    """--trace: install the process-wide span tracer; the journal notes
    the trace path so obs_report readers find the matching timeline."""
    if not args.trace:
        return None
    from deep_vision_tpu.obs import Tracer, set_tracer

    tracer = Tracer(args.trace,
                    run_id=journal.run_id if journal is not None else None)
    set_tracer(tracer)
    if journal is not None:
        journal.write("note", trace_path=args.trace)
    return tracer


def _make_health(args, journal):
    """--health-policy / --watchdog-timeout: the run's health monitor.
    Either flag alone activates it (a watchdog with the default `warn`
    NaN policy, or a NaN policy with no hang deadline)."""
    if not args.health_policy and not args.watchdog_timeout:
        return None
    from deep_vision_tpu.obs import HealthMonitor

    health = HealthMonitor(
        policy=args.health_policy or "warn",
        journal=journal,
        watchdog_timeout=args.watchdog_timeout,
        # --watchdog-timeout alone: the 'warn' NaN policy is a default the
        # user never chose, so it must not soften the trainer's
        # pre-existing fatal divergence check
        policy_explicit=args.health_policy is not None,
    )
    if journal is not None:
        # stop() is idempotent: the closer covers abnormal unwinds, the
        # explicit stop in _finish_obs covers clean exits
        journal.add_closer(health.stop)
    return health


def _make_flight(args, journal):
    """--flight-dir: install the flight recorder (obs/flight.py). It taps
    the journal for its postmortem ring buffers and registers as the
    process-wide recorder so the preemption guard, fault injector, and
    data pipeline can reach it without a handle."""
    if not args.flight_dir:
        return None
    from deep_vision_tpu.obs import FlightRecorder, set_flight

    flight = FlightRecorder(
        args.flight_dir,
        run_id=journal.run_id if journal is not None else None)
    set_flight(flight)
    if journal is not None:
        flight.attach(journal)
    return flight


def _make_telemetry(args, journal, flight, discovery_dir,
                    role: str = "train"):
    """--telemetry-port / DVT_TELEMETRY: the live observability plane
    (obs/telemetry.py). Failure to bind degrades to a warning — the
    telemetry plane must never kill the run it observes."""
    port = args.telemetry_port
    if port is None:
        from deep_vision_tpu.core import knobs

        try:
            port = knobs.get_int("DVT_TELEMETRY")
        except knobs.KnobError as e:
            # degrade, don't raise: the telemetry plane must never kill
            # the run it observes — not even at parse time
            print(f"warning: {e}; telemetry disabled", file=_sys.stderr)
            return None
    if port is None:
        return None
    from deep_vision_tpu.obs.registry import get_registry
    from deep_vision_tpu.obs.telemetry import TelemetryServer

    tele = TelemetryServer(port=port, role=role, registry=get_registry(),
                           journal=journal, flight=flight,
                           discovery_dir=discovery_dir)
    try:
        tele.start()
    except OSError as e:
        print(f"warning: telemetry server failed to bind port {port} "
              f"({e}); continuing without live endpoints",
              file=_sys.stderr)
        return None
    if journal is not None:
        # abnormal unwinds: close is idempotent, the clean path in
        # _finish_obs re-running it is a no-op
        journal.add_closer(tele.close)
    print(f"telemetry: http://{tele.address}/statusz")
    return tele


def _parse_profile_window(parser, spec: str):
    try:
        start_s, stop_s = spec.split(":")
        start, stop = int(start_s), int(stop_s)
    except ValueError:
        parser.error(f"--profile-window {spec!r} is not 'START:STOP'")
    if not 0 <= start < stop:
        parser.error(f"--profile-window needs 0 <= START < STOP, got {spec}")
    return start, stop


def _make_autoprof(args, journal, default_dir: str, window=None):
    """--profile-dir (static window) / --autoprof (anomaly triggers):
    one AutoProfiler owns both capture modes (obs/autoprof.py)."""
    if not args.profile_dir and not args.autoprof:
        return None
    from deep_vision_tpu.obs import AutoProfiler

    # --autoprof without --profile-dir still needs somewhere to put the
    # captures; the checkpoint dir is the run's natural artifact home
    pdir = args.profile_dir or os.path.join(default_dir, "autoprof")
    return AutoProfiler(
        pdir, journal=journal,
        # the static window applies only when the user asked for a static
        # capture dir; pure --autoprof runs capture on anomalies alone
        window=window if args.profile_dir else None,
        auto=args.autoprof,
        window_steps=args.autoprof_window,
        cooldown_steps=args.autoprof_cooldown,
        max_captures=args.autoprof_budget,
        z_threshold=args.autoprof_z,
    )


def _finish_obs(args, journal, status: str = "clean_exit",
                tracer=None, health=None, autoprof=None,
                flight=None, telemetry=None) -> None:
    """Clean-run epilogue: Prometheus export + trace flush + journal exit
    marker + multi-host journal aggregation + flight disarm. (Abnormal
    exits are covered by the journal's atexit crash marker, the tracer's
    atexit flush, the health closer, and the flight recorder's atexit
    crash dump.)"""
    if telemetry is not None:
        # first: stop answering scrapes before the sources below tear down
        # (a probe against a half-closed run would read freed state)
        telemetry.close()
    if autoprof is not None:
        autoprof.close()  # stop an in-flight capture instead of leaking it
    if health is not None:
        health.stop()
    if tracer is not None:
        from deep_vision_tpu.obs import set_tracer

        tracer.close()
        set_tracer(None)
        print(f"trace written to {tracer.path} "
              "(load in Perfetto / chrome://tracing)")
    if journal is not None:
        journal.close(status)
        # multi-host: every host closed its .pN file at the barrier inside
        # aggregate_obs; the primary stitches them into one timeline with
        # cross-host straggler detection (no-op single-process)
        try:
            from deep_vision_tpu.parallel.multihost import aggregate_obs

            merged = aggregate_obs(args.journal)
            if merged:
                print(f"merged multi-host journal -> {merged} "
                      "(render with tools/obs_report.py --merged)")
        except Exception as e:
            print(f"warning: multi-host journal merge failed: {e}")
    # metrics export AFTER the merge: counters the aggregation itself
    # bumps (obs_straggler_total) must land in the exported snapshot
    if args.metrics_export:
        from deep_vision_tpu.obs.registry import get_registry

        if get_registry().write_prometheus(args.metrics_export):
            print(f"metrics exported to {args.metrics_export}")
    if flight is not None:
        flight.close()  # clean exit: disarm, no crash bundle


# -- main --------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="deep_vision_tpu trainer (train.py -m <config> [-c ckpt])"
    )
    parser.add_argument("-m", "--model", required=True,
                        choices=sorted(CONFIG_REGISTRY))
    parser.add_argument("-c", "--checkpoint", default=None,
                        help="resume: checkpoint dir (or 'auto' for default dir)")
    parser.add_argument("--data-dir", default="./dataset")
    parser.add_argument("--ckpt-dir", default=None)
    parser.add_argument("--epochs", type=int, default=None)
    parser.add_argument("--batch-size", type=int, default=None)
    parser.add_argument("--num-workers", type=int, default=8,
                        help="decode thread pool size")
    parser.add_argument("--num-procs", type=int, default=0,
                        help="decode worker PROCESSES (0 = threads only); "
                             "use ~cores/2 on big hosts to scale JPEG decode "
                             "past the GIL")
    parser.add_argument("--fake-data", action="store_true")
    parser.add_argument("--fake-batches", type=int, default=4)
    parser.add_argument("--tensorboard-dir", default=None)
    parser.add_argument("--profile-dir", default=None,
                        help="capture a jax.profiler trace of the "
                             "--profile-window steps into this dir")
    parser.add_argument("--profile-window", default="10:20",
                        metavar="START:STOP",
                        help="static capture window [START, STOP) for "
                             "--profile-dir (default 10:20)")
    parser.add_argument("--autoprof", action="store_true",
                        help="anomaly-triggered profiling: step-time/"
                             "data-wait z-score regressions, recompile "
                             "bursts, and HBM high-water jumps each arm a "
                             "one-shot N-step jax.profiler capture with "
                             "cooldown and budget, journaled as typed "
                             "profile_capture events (obs/autoprof.py)")
    parser.add_argument("--autoprof-window", type=int, default=8,
                        metavar="STEPS",
                        help="steps per triggered capture (default 8)")
    parser.add_argument("--autoprof-cooldown", type=int, default=200,
                        metavar="STEPS",
                        help="steps after a capture before another trigger "
                             "may arm (default 200)")
    parser.add_argument("--autoprof-budget", type=int, default=2,
                        metavar="N",
                        help="max triggered captures per run (default 2; "
                             "the static --profile-window is exempt)")
    parser.add_argument("--autoprof-z", type=float, default=5.0,
                        metavar="Z",
                        help="rolling z-score threshold for the step-time/"
                             "data-wait regression triggers (default 5.0)")
    parser.add_argument("--flight-dir", default=None, metavar="DIR",
                        help="always-on flight recorder: ring-buffer the "
                             "recent steps/health/journal/span tail and "
                             "dump an atomic crc-checked postmortem bundle "
                             "under DIR on crash, hang, health abort, or "
                             "preemption (obs/flight.py; validate with "
                             "obs.flight.validate_bundle)")
    parser.add_argument("--journal", default=None, metavar="PATH",
                        help="append typed run events (manifest, per-step "
                             "timing, eval/checkpoint, exit marker) to this "
                             "JSONL; render with tools/obs_report.py")
    parser.add_argument("--metrics-export", default=None, metavar="PATH",
                        help="write the metrics registry as Prometheus text "
                             "exposition format at the end of the run")
    parser.add_argument("--telemetry-sample-every", type=int, default=16,
                        help="block_until_ready fence cadence for the "
                             "step-time breakdown (obs/stepclock.py)")
    parser.add_argument("--telemetry-port", type=int, default=None,
                        metavar="PORT",
                        help="serve live /metrics /varz /healthz /statusz "
                             "over HTTP on PORT (0 = auto-assign; the bound "
                             "port is journaled as a telemetry_server event "
                             "and written to a discovery file under the "
                             "checkpoint dir for tools/obs_poll.py). "
                             "DVT_TELEMETRY=PORT is the env equivalent "
                             "(obs/telemetry.py)")
    parser.add_argument("--trace", default=None, metavar="PATH",
                        help="write Chrome trace-event JSON spans (data "
                             "fetch/augment, train/eval steps, checkpoint "
                             "I/O) to this path; load in Perfetto or "
                             "chrome://tracing (obs/trace.py)")
    parser.add_argument("--health-policy", default=None,
                        choices=["warn", "skip_step", "abort"],
                        help="NaN/Inf + divergence guard on per-step loss "
                             "and grad norm: 'warn' logs and continues, "
                             "'skip_step' discards the poisoned update "
                             "inside the jitted step, 'abort' writes a "
                             "typed health journal event and raises "
                             "(obs/health.py)")
    parser.add_argument("--watchdog-timeout", type=float, default=None,
                        metavar="SECONDS",
                        help="hang detector: if no step completes within "
                             "this deadline, dump every thread's stack to "
                             "stderr and a 'health' journal event (a hung "
                             "multi-host collective stays diagnosable "
                             "post-mortem)")
    parser.add_argument("--skip-preflight", action="store_true",
                        help="skip the environment preflight (backend "
                             "liveness + version handshake, mesh-shape "
                             "sanity, checkpoint-dir writability) that "
                             "otherwise runs first so a doomed run fails "
                             "in seconds instead of minutes "
                             "(tools/preflight.py, `make preflight`)")
    parser.add_argument("--backend-retries", type=int, default=0,
                        metavar="N",
                        help="treat a lost backend (dropped connection, "
                             "dead-tunnel timeout) as an expected input: "
                             "rebuild the jitted step, restore the last "
                             "checkpoint, and replay, up to N times — "
                             "journaled as typed backend_lost/"
                             "backend_recovered events "
                             "(resilience/elastic.py BackendSupervisor; "
                             "0 = fail on the first backend error)")
    parser.add_argument("--fault-spec", default=None, metavar="SPEC",
                        help="inject deterministic faults at named I/O "
                             "points (resilience/faults.py), e.g. "
                             "'data.read:io_error@0.01;ckpt.sidecar:"
                             "crash_after_write'; exported to data-worker "
                             "processes via the environment")
    parser.add_argument("--fault-seed", type=int, default=0,
                        help="seed for probabilistic fault rules (same seed "
                             "= same fault sequence)")
    parser.add_argument("--data-snapshot", action="store_true",
                        help="checkpoint the input pipeline with the model "
                             "(data/snapshot.py): every save's host sidecar "
                             "carries the train DataLoader's position "
                             "(epoch, batches, shard cursor, bad-record-"
                             "budget spend) and resume replays a byte-"
                             "identical batch stream instead of restarting "
                             "from shard zero (typed data_resume event; "
                             "requires a real dataset, --num-procs 0)")
    parser.add_argument("--data-service", default=None, metavar="HOST:PORT",
                        help="consume training batches from a shared "
                             "dataset service (data/service.py; run one "
                             "with tools/data_service.py) instead of a "
                             "local pipeline — decode/augment leave this "
                             "process, several trainers/evals share one "
                             "stream, reconnects ride the retry policy")
    parser.add_argument("--data-service-steps", type=int, default=64,
                        metavar="N",
                        help="batches per epoch window when consuming "
                             "--data-service (the service stream is "
                             "continuous; epochs are client-side)")
    parser.add_argument("--bad-record-budget", default=None, metavar="N|FRAC",
                        help="skip corrupt/undecodable records instead of "
                             "crashing, up to this many (>=1) or this "
                             "fraction (<1) of records seen; each skip is "
                             "dead-lettered with file+offset, and the run "
                             "aborts once the budget is spent (per worker "
                             "process with --num-procs)")
    parser.add_argument("--dead-letter", default=None, metavar="PATH",
                        help="dead-letter JSONL for skipped records "
                             "(default: <ckpt-dir>/dead_letter.jsonl)")
    parser.add_argument("--eval-first", action="store_true",
                        help="epoch-0 sanity validate (ResNet/pytorch/train.py:390)")
    parser.add_argument("--eval-only", action="store_true",
                        help="no training: evaluate the checkpoint on the val "
                             "split (classification loss/top-k, detection mAP, "
                             "pose PCK)")
    parser.add_argument("--preprocessing", default="torch",
                        choices=["torch", "tf"],
                        help="ImageNet chain: torchvision stats or the TF "
                             "0-255 mean-subtraction variant")
    parser.add_argument("--summary", action="store_true",
                        help="print the per-parameter model summary table "
                             "(torchsummary analog) before training")
    parser.add_argument("--multistep", type=int, default=1, metavar="K",
                        help="optimizer steps per device dispatch via a "
                             "lax.scan superstep: one dispatch carries K "
                             "stacked batches, amortizing host dispatch "
                             "overhead K-fold; per-step metrics/NaN-guard "
                             "are preserved and step counters advance by K "
                             "per dispatch (incompatible with --checkify "
                             "and --ema-decay)")
    parser.add_argument("--device-prefetch", type=int, default=0,
                        metavar="DEPTH",
                        help="pad/shard/device_put the next DEPTH batches "
                             "on a producer thread so H2D transfer overlaps "
                             "compute (2 = double buffering; 0 = place on "
                             "the critical path as before); depth/starvation "
                             "ride the device_prefetch_* metrics")
    parser.add_argument("--sharding-rules", default=None, metavar="TABLE",
                        help="declarative pattern->PartitionSpec sharding "
                             "table (parallel/shardmap.py): a family name "
                             "(vit/moe/resnet), 'auto' (derive from the "
                             "model, refusing families without a table), or "
                             "'heuristic' (the explicit infer_tp_sharding "
                             "size-heuristic fallback). The full train state "
                             "places per the table, coverage is hard-checked "
                             "at startup against the family's floor, and the "
                             "rule->leaf resolution is journaled as a typed "
                             "sharding_resolved event")
    parser.add_argument("--executable-cache", default=None, metavar="DIR",
                        help="persistent compiled-executable cache dir "
                             "(core/excache.py; env DVT_EXCACHE): step "
                             "executables AOT-round-trip through the "
                             "content-addressed store so a restarted "
                             "process, a backend-loss rebuild, or a "
                             "re-exec'd host loads instead of recompiling; "
                             "also points jax_compilation_cache_dir at "
                             "DIR/xla for the jit-traced leftovers")
    parser.add_argument("--opt-state-dtype", default=None,
                        choices=["bfloat16", "float32"],
                        help="storage dtype for optimizer state (momentum/"
                             "Adam moments): bfloat16 halves the update's "
                             "HBM traffic; the update still computes in f32 "
                             "and the injected LR stays f32")
    parser.add_argument("--ema-decay", type=float, default=None,
                        help="maintain an EMA of the weights at this decay "
                             "and evaluate with it (train/ema.py)")
    parser.add_argument("--checkify", action="store_true",
                        help="run the train step under jax.experimental."
                             "checkify (NaN/out-of-bounds/div0 checks on "
                             "every op, ~2x step cost) and raise a located "
                             "error — the compiled-mode sanitizer")
    parser.add_argument("--debug-nans", action="store_true",
                        help="jax_debug_nans: re-run the op that produced "
                             "the first NaN un-jitted and raise there (the "
                             "sanitizer analog; SURVEY §5 'race detection/"
                             "sanitizers: NONE' upstream)")
    parser.add_argument("--upload-to", default=None,
                        help="after training, upload the checkpoint dir to "
                             "this destination (gs://, s3://, or a local/"
                             "file:// path) — the cloud-run hook from "
                             "Hourglass/tensorflow/main.py:50-65")
    args = parser.parse_args(argv)

    # the requeue latch is process-wide and main() may be called more than
    # once per process (tests, notebooks): this run's verdict starts clean
    from deep_vision_tpu.obs import flight as _flight_mod

    _flight_mod.clear_requeue()
    # executable cache (core/excache.py): env fallback + jax's own
    # persistent compilation cache installed BEFORE anything compiles
    # (preflight's probe op would otherwise be the first, uncached one)
    if not args.executable_cache:
        from deep_vision_tpu.core import knobs
        from deep_vision_tpu.core.excache import EXCACHE_ENV

        args.executable_cache = knobs.get_str(EXCACHE_ENV)
    if args.executable_cache:
        from deep_vision_tpu.core.excache import install_jax_compilation_cache

        install_jax_compilation_cache(
            os.path.join(args.executable_cache, "xla"))
    if args.debug_nans:
        import jax as _jax_cfg

        _jax_cfg.config.update("jax_debug_nans", True)
    cfg = get_config(args.model)

    # environment preflight FIRST (tools/preflight.py): a dead tunnel, a
    # libtpu version skew, or an unwritable checkpoint volume fails here
    # in seconds — before any dataloader, compile, or epoch burns minutes
    # proving the same thing (MULTICHIP_r01 died 4 minutes in on what this
    # catches up front)
    if not args.skip_preflight:
        from deep_vision_tpu.tools.preflight import render, run_preflight

        pf_ckpt = args.ckpt_dir or os.path.join("checkpoints", cfg.name)
        if args.checkpoint and args.checkpoint != "auto":
            pf_ckpt = args.checkpoint  # saves follow the resume dir
        pf_ok, pf_results = run_preflight(
            ckpt_dir=pf_ckpt, excache_dir=args.executable_cache)
        if not pf_ok:
            render(pf_results)
            print("preflight FAILED: fix the environment (or pass "
                  "--skip-preflight to proceed anyway)", flush=True)
            return 1
    if args.epochs is not None:
        cfg.epochs = args.epochs
    if args.batch_size is not None:
        cfg.batch_size = args.batch_size
    # declarative sharding table (parallel/shardmap.py): resolved here so
    # an unknown family/typo is a usage error before any loader is built
    sharding_rules = None
    if args.sharding_rules:
        from deep_vision_tpu.parallel.shardmap import (
            ShardingRuleError,
            get_rules,
        )

        try:
            sharding_rules = get_rules(args.sharding_rules, cfg.model)
        except ShardingRuleError as e:
            parser.error(str(e))
    # per-host sharded loading (multihost.host_shard): in a multi-host
    # world each host reads only its disjoint record-shard slice; the
    # value routes through the CURRENT rendezvous generation, so the
    # elastic layer's per-generation re-derive is inherited for free.
    # Single-host runs pass None — loader fingerprints stay unchanged.
    host_shard = None
    from deep_vision_tpu.parallel import multihost as _mh

    if _mh.process_count() > 1:
        host_shard = _mh.host_shard()
    if args.preprocessing == "tf" and (
        args.fake_data or cfg.dataset.get("kind") != "imagenet"
    ):
        print("warning: --preprocessing tf only applies to the ImageNet "
              "records/folder pipeline; this run uses its default chain")

    # faults install BEFORE any data/checkpoint object is built so loader
    # construction is already covered; the journal attaches once it exists
    if args.fault_spec:
        from deep_vision_tpu.resilience import install_spec

        install_spec(args.fault_spec, seed=args.fault_seed)
        print(f"faults: installed spec {args.fault_spec!r} "
              f"(seed {args.fault_seed})")
    budget = None
    if args.bad_record_budget:
        from deep_vision_tpu.data.records import BadRecordBudget

        default_ckpt = args.ckpt_dir or os.path.join("checkpoints", cfg.name)
        budget = BadRecordBudget.parse(
            args.bad_record_budget,
            dead_letter_path=args.dead_letter or os.path.join(
                default_ckpt, "dead_letter.jsonl"),
        )

    if args.data_service:
        # the trainer consumes the shared service — local data is only
        # needed for the eval split, so its absence must not kill the
        # run (the documented service-consumer invocation passes no
        # --data-dir at all); train_fn is replaced by the service
        # client below either way
        try:
            train_fn, eval_fn = build_dataloaders(
                cfg, args.data_dir, args.fake_data, args.fake_batches,
                args.num_workers, preprocessing=args.preprocessing,
                num_procs=args.num_procs, bad_record_budget=budget,
                host_shard=host_shard,
            )
        except (FileNotFoundError, OSError) as e:
            print(f"--data-service: no local eval dataset ({e}); "
                  "training without an eval split")
            train_fn, eval_fn = (lambda: []), None
        if args.eval_only and eval_fn is None:
            parser.error("--eval-only needs a local eval dataset, which "
                         "--data-service could not find")
    else:
        train_fn, eval_fn = build_dataloaders(
            cfg, args.data_dir, args.fake_data, args.fake_batches,
            args.num_workers, preprocessing=args.preprocessing,
            num_procs=args.num_procs, bad_record_budget=budget,
            host_shard=host_shard,
        )

    if cfg.task in ("dcgan", "cyclegan"):
        if sharding_rules is not None:
            parser.error(
                "--sharding-rules rides the standard Trainer state "
                f"placement; GAN task {cfg.task!r} has its own G/D "
                "trainers without it")
        if args.eval_only:
            parser.error(
                f"--eval-only is not supported for GAN task {cfg.task!r} "
                "(no scalar quality metric; use the sample grids instead)"
            )
        if args.data_service or args.data_snapshot:
            parser.error(
                "--data-service/--data-snapshot ride the standard Trainer "
                f"checkpoint/resume path; GAN task {cfg.task!r} has its own "
                "loop without them"
            )
        import jax as _jax

        from deep_vision_tpu.core.summary import count_params

        journal = _make_journal(args, cfg, budget=budget)
        tracer = _make_tracer(args, journal)
        health = _make_health(args, journal)
        flight = _make_flight(args, journal)
        autoprof = _make_autoprof(
            args, journal, args.ckpt_dir or os.path.join("checkpoints",
                                                         cfg.name),
            window=_parse_profile_window(parser, args.profile_window))
        telemetry = _make_telemetry(
            args, journal, flight,
            args.ckpt_dir or os.path.join("checkpoints", cfg.name))
        if telemetry is not None and health is not None:
            telemetry.add_health("train", health.healthz)
        trainer = build_gan_trainer(
            cfg, journal=journal,
            telemetry_sample_every=args.telemetry_sample_every,
            health=health, autoprof=autoprof)
        if journal is not None:
            journal.write("note", mesh_shape=dict(trainer.mesh.shape))
        states = (
            {"G": trainer.g_state, "D": trainer.d_state}
            if cfg.task == "dcgan"
            else {"G_ab": trainer.gab, "G_ba": trainer.gba,
                  "D_a": trainer.da, "D_b": trainer.db}
        )
        print("model " + cfg.model + ": " + " ".join(
            f"{k}={count_params(s.params):,}" for k, s in states.items()
        ) + " trainable params")
        if args.summary:
            from deep_vision_tpu.core.summary import model_summary
            from deep_vision_tpu.models import get_model as _gm
            import jax.numpy as _jnp

            img = _jnp.ones((2, *cfg.input_shape), _jnp.float32)
            if cfg.task == "dcgan":
                parts = {"G": (_gm("dcgan_generator"), _jnp.ones((2, 100))),
                         "D": (_gm("dcgan_discriminator"), img)}
            else:
                parts = {"G": (_gm("cyclegan_generator"), img),
                         "D": (_gm("cyclegan_discriminator"), img)}
            for k, (mod, sample) in parts.items():
                print(f"-- {k} --")
                print(model_summary(mod, sample))
        # checkpoint/resume: the reference GAN trainers capture G/D/optimizers
        # + epoch and restore-or-initialize (CycleGAN/tensorflow/train.py:
        # 133-148; DCGAN/tensorflow/main.py:34-40); CycleGAN saves every 2
        # epochs (:329-333), DCGAN every epoch with max_to_keep=3 (:40,80-83)
        from deep_vision_tpu.core import CheckpointManager

        start_epoch = 0
        gan_save_every = 2 if cfg.task == "cyclegan" else 1
        ckpt_dir = args.ckpt_dir or os.path.join("checkpoints", cfg.name)
        if args.checkpoint and args.checkpoint != "auto":
            ckpt_dir = args.checkpoint
        gan_ckpt = CheckpointManager(
            ckpt_dir,
            max_to_keep=3 if cfg.task == "dcgan" else None,
            journal=journal,
        )
        if args.checkpoint:
            start_epoch = trainer.restore(gan_ckpt)
            if start_epoch:
                print(f"resumed GAN training at epoch {start_epoch}")
        # preemption-safe like Trainer.fit, via the SAME mechanism
        # (multihost.PreemptionGuard: SIGTERM handler + cross-host
        # consensus at a deterministic cadence)
        from deep_vision_tpu.parallel.multihost import PreemptionGuard

        if health is not None:
            health.start_watchdog()  # no-op without --watchdog-timeout
        with PreemptionGuard() as guard:
            for epoch in range(start_epoch, cfg.epochs):
                # keep per-step metrics as device arrays; float() only at epoch
                # end so the host never blocks async dispatch mid-epoch
                collected: list = []
                interrupted = False
                # poll keyed to the batch index — host-identical (sharded
                # drop_remainder loaders yield equal counts), so every host
                # rendezvouses at the same boundary
                for batch_i, batch in enumerate(
                        trainer.clock.iter_data(train_fn())):
                    if guard.agreed(step=batch_i):
                        interrupted = True
                        break
                    if cfg.task == "dcgan":
                        metrics = trainer.train_step(batch["image"])
                    else:
                        half = len(batch["image"]) // 2 or 1
                        metrics = trainer.train_step(
                            batch["image"][:half], batch["image"][half:half * 2]
                        )
                    collected.append(metrics)
                if collected and not interrupted:
                    # (suppressed on preemption: a partial-epoch summary would
                    # duplicate the re-run epoch's row, as in Trainer.fit)
                    collected = _jax.device_get(collected)  # one host round-trip
                    keys = sorted(collected[0])
                    summary = {
                        k: sum(float(m[k]) for m in collected) / len(collected)
                        for k in keys
                    }
                    print(f"epoch {epoch}: " + " ".join(
                        f"{k}={v:.4f}" for k, v in summary.items()
                    ))
                    if journal is not None:
                        journal.write("epoch", name="gan", epoch=epoch,
                                      summary=summary)
                    # epoch-granularity NaN guard: the GAN loop keeps
                    # per-step metrics on device, so the summary is the
                    # first host-visible place divergence can be caught
                    if health is not None:
                        health.check_summary(epoch, summary)
                if guard.agreed(force=True):
                    # interrupted: mid-epoch states saved under the global
                    # optimizer step, marked so resume re-runs this epoch; a
                    # loop that ran to completion saves the epoch as complete
                    done = epoch if not interrupted else epoch - 1
                    saved = trainer.save(gan_ckpt, epoch, completed_epoch=done)
                    gan_ckpt.wait()
                    print(f"preempted in epoch {epoch}: "
                          + ("checkpoint written" if saved
                             else "checkpoint DECLINED (nothing new to save)"))
                    # same SIGTERM escalation as Trainer._preempt_save:
                    # typed event + the scheduler's requeue exit code
                    if journal is not None:
                        journal.write(
                            "preempt_checkpoint",
                            step=int(gan_ckpt.latest_step() or 0),
                            epoch=epoch, saved=bool(saved),
                            dir=ckpt_dir)
                    _flight_mod.request_requeue()
                    break
                if (epoch + 1) % gan_save_every == 0:
                    trainer.save(gan_ckpt, epoch)
        gan_ckpt.wait()
        _maybe_upload(args, ckpt_dir)
        _finish_obs(args, journal, tracer=tracer, health=health,
                    autoprof=autoprof, flight=flight, telemetry=telemetry)
        # a graceful preemption exits with the requeue code (EX_TEMPFAIL):
        # the scheduler resubmits and the run resumes from the preempt
        # checkpoint — on whatever mesh the new allocation provides
        return (_flight_mod.REQUEUE_EXIT_CODE
                if _flight_mod.requeue_requested() else 0)

    ckpt_dir = args.ckpt_dir or os.path.join("checkpoints", cfg.name)
    journal = _make_journal(args, cfg, budget=budget)
    tracer = _make_tracer(args, journal)
    health = _make_health(args, journal)
    flight = _make_flight(args, journal)
    autoprof = _make_autoprof(
        args, journal, ckpt_dir,
        window=_parse_profile_window(parser, args.profile_window))
    telemetry = _make_telemetry(args, journal, flight, ckpt_dir)
    # -- the data plane's two new modes (data/service.py, data/snapshot.py)
    if args.data_snapshot and args.data_service:
        # refuse BEFORE any client/loader is built: a constructed client
        # would register a journal closer and stamp a phantom
        # data_service summary into a run that never happened
        parser.error(
            "--data-snapshot checkpoints the LOCAL pipeline; a "
            "--data-service stream is shared across consumers and "
            "snapshots nothing (its resume story is the trainer's "
            "step checkpoint + the service's own restart)")
    data_client = None
    if args.data_service:
        from deep_vision_tpu.data.service import DataServiceClient

        data_client = DataServiceClient(args.data_service, name=cfg.name,
                                        journal=journal)
        svc_steps = args.data_service_steps
        train_fn = lambda: data_client.batches(svc_steps)  # noqa: E731
        if journal is not None:
            # closer covers abnormal unwinds; the clean path closes below
            journal.add_closer(data_client.close)
    data_loader = None
    if args.data_snapshot:
        cand = train_fn()
        if (hasattr(cand, "snapshot_supported")
                and cand.snapshot_supported()):
            data_loader = cand
        else:
            parser.error(
                "--data-snapshot needs a snapshot-capable DataLoader: a "
                "real dataset (not --fake-data) with --num-procs 0")
    supervisor = None
    if args.backend_retries > 0:
        from deep_vision_tpu.resilience.elastic import BackendSupervisor

        supervisor = BackendSupervisor(max_retries=args.backend_retries,
                                       journal=journal, name="train.backend")
    excache = None
    if args.executable_cache:
        from deep_vision_tpu.core.excache import ExecutableCache

        excache = ExecutableCache(args.executable_cache, journal=journal)
    trainer = build_trainer(cfg, train_fn, ckpt_dir,
                            tb_dir=args.tensorboard_dir,
                            checkify_errors=args.checkify,
                            ema_decay=args.ema_decay,
                            journal=journal,
                            telemetry_sample_every=args.telemetry_sample_every,
                            health=health, autoprof=autoprof,
                            multistep=args.multistep,
                            device_prefetch=args.device_prefetch,
                            opt_state_dtype=(
                                None if args.opt_state_dtype == "float32"
                                else args.opt_state_dtype),
                            backend_supervisor=supervisor,
                            data_loader=data_loader,
                            steps_per_epoch=(args.data_service_steps
                                             if args.data_service else None),
                            executable_cache=excache,
                            sharding_rules=sharding_rules,
                            telemetry=telemetry)
    if journal is not None:
        # an unwinding run (exception/SIGTERM) still stops an in-flight
        # profiler trace and flushes writers via the atexit crash path
        journal.add_closer(trainer.close)
        journal.write("note", mesh_shape=dict(trainer.mesh.shape))
    # param accounting before training, like summary(net, (3,224,224)) at
    # ResNet/pytorch/train.py:350 / model.summary() at YOLO/tensorflow/train.py:297
    from deep_vision_tpu.core.summary import count_params

    if args.summary:
        from deep_vision_tpu.core.summary import model_summary
        import jax.numpy as _jnp

        # summarize the exact module build_trainer constructed, not a rebuild
        print(model_summary(
            trainer.model, _jnp.ones((2, *model_input_shape(cfg)), _jnp.float32)
        ))
    print(f"model {cfg.model}: {count_params(trainer.state.params):,} trainable params")
    start_epoch = 0
    if args.checkpoint:
        if args.checkpoint != "auto":
            # saves (and the end-of-run upload) follow the resume dir
            ckpt_dir = args.checkpoint
            trainer.ckpt = type(trainer.ckpt)(ckpt_dir, journal=journal)
        start_epoch = trainer.resume()
        print(f"resumed from step {int(trainer.state.step)} -> epoch {start_epoch}")
    if args.eval_only:
        run_eval_only(cfg, trainer, eval_fn)
        trainer.close()
        _finish_obs(args, journal, tracer=tracer, health=health,
                    autoprof=autoprof, flight=flight, telemetry=telemetry)
        return 0
    trainer.fit(
        train_fn, eval_fn, epochs=cfg.epochs, start_epoch=start_epoch,
        eval_first=args.eval_first,
    )
    trainer.close()
    if data_client is not None:
        data_client.close()  # idempotent: the journal closer may re-run it
    _maybe_upload(args, ckpt_dir)
    _finish_obs(args, journal, tracer=tracer, health=health,
                autoprof=autoprof, flight=flight, telemetry=telemetry)
    # SIGTERM escalation epilogue: the preempt checkpoint is on disk and
    # journaled — exit with the requeue code so the scheduler resubmits
    # (resume rides the cross-mesh restore if the new slice is smaller)
    return (_flight_mod.REQUEUE_EXIT_CODE
            if _flight_mod.requeue_requested() else 0)


if __name__ == "__main__":
    raise SystemExit(main())
