"""The front door + process fleet tier-1 suite: HTTP status mapping for
every shed reason (429/503 + Retry-After), deadline sheds at admission
vs at dispatch (504, never executed), W3C traceparent riding the socket
into the journal, torn-frame fault scoping at the transport point, the
retrying HTTP client honoring Retry-After, and the process-replica
fleet: SIGKILL -> typed replica_lost -> zero-compile respawn (excache
counters asserted) with the fleet ledger balanced across the episode.

The sustained-RPS socket scenario with a mid-traffic SIGKILL is
`make fleetnet-smoke` (tools/fleetnet_smoke.py); this suite pins the
contracts piece by piece.
"""
import http.client
import json
import os
import signal
import time
from concurrent.futures import Future

import numpy as np
import pytest

from deep_vision_tpu.obs import RunJournal, propagate, read_journal
from deep_vision_tpu.obs.registry import Registry
from deep_vision_tpu.resilience import faults
from deep_vision_tpu.serve import (
    DEADLINE_HEADER,
    SHED_REASONS,
    STATUS_BY_REASON,
    TRANSPORT_OUTCOMES,
    DeadlineExceeded,
    Engine,
    ProcReplicaPool,
    ReplicaLost,
    Server,
    ShedError,
    Transport,
)

IMG = (4, 4, 1)


def toy_fn(variables, images):
    flat = images.reshape((images.shape[0], -1))
    return {"scores": flat @ variables["w"]}


def toy_variables(scale=1.0, seed=0):
    import jax.numpy as jnp

    w = np.random.RandomState(seed).randn(16, 3).astype(np.float32) * scale
    return {"w": jnp.asarray(w)}


def an_image(seed=1):
    return np.random.RandomState(seed).rand(*IMG).astype(np.float32)


class FakeBackend:
    """In-memory backend: records calls + ambient trace context, answers
    instantly (or with the exception the test arms)."""

    def __init__(self, fail_with=None):
        self.calls = []
        self.ctxs = []
        self.fail_with = fail_with

    def submit(self, model, image, deadline_ms=None):
        self.calls.append((model, deadline_ms))
        self.ctxs.append(propagate.current())
        fut = Future()
        if self.fail_with is not None:
            fut.set_exception(self.fail_with)
        else:
            fut.set_result({"scores": [1.0, 2.0, 3.0]})
        return fut


class StubAdmission:
    """admit() answers from a scripted reason list (None = admitted)."""

    def __init__(self, reasons):
        self.reasons = list(reasons)
        self.depths = []

    def admit(self, model, queue_depth):
        self.depths.append(queue_depth)
        return self.reasons.pop(0) if self.reasons else None


def post(port, path, body, headers=None, timeout=10):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path,
                     body=body if isinstance(body, bytes)
                     else json.dumps(body).encode("utf-8"),
                     headers=headers or {})
        r = conn.getresponse()
        raw = r.read()
        return r.status, {k.lower(): v for k, v in r.getheaders()}, \
            json.loads(raw) if raw else None
    finally:
        conn.close()


def get(port, path, timeout=10):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, json.loads(r.read())
    finally:
        conn.close()


@pytest.fixture
def registry():
    return Registry()


def make_transport(tmp_path, registry, backend=None, **kw):
    journal = RunJournal(os.path.join(str(tmp_path), "journal.jsonl"),
                        kind="serve")
    kw.setdefault("models", ["toy"])
    tp = Transport(backend or FakeBackend(), journal=journal,
                   registry=registry, **kw).start()
    return tp, journal


class TestStatusMapping:
    def test_every_shed_reason_maps_to_its_status(self, tmp_path, registry):
        # the contract table itself: 429 only for rate_limited, 503 for
        # the capacity/lifecycle sheds
        assert STATUS_BY_REASON == {"rate_limited": 429,
                                    "queue_full": 503, "draining": 503}
        assert set(STATUS_BY_REASON) == set(SHED_REASONS)
        tp, journal = make_transport(
            tmp_path, registry,
            admission=StubAdmission(list(SHED_REASONS)))
        try:
            img = an_image().tolist()
            for reason in SHED_REASONS:
                st, hdrs, payload = post(tp.port, "/v1/toy", {"image": img})
                assert st == STATUS_BY_REASON[reason], (reason, payload)
                assert payload["reason"] == reason
                assert payload["retryable"] is True
                # Retry-After rides EVERY shed: seconds, decimal form
                assert float(hdrs["retry-after"]) > 0
            st, _, payload = post(tp.port, "/v1/toy", {"image": img})
            assert st == 200  # script exhausted: admitted
        finally:
            tp.close()
            journal.close()
        led = tp.ledger()
        assert led["shed"] == 3 and led["ok"] == 1 and led["balanced"]
        assert led["by_status"] == {"429": 1, "503": 2, "200": 1}
        evs = [e for e in read_journal(journal.path)
               if e.get("event") == "transport_request"]
        assert [e["outcome"] for e in evs] == ["shed"] * 3 + ["ok"]
        assert sorted(e["status"] for e in evs) == [200, 429, 503, 503]

    def test_backend_shed_maps_like_admission_shed(self, tmp_path,
                                                   registry):
        # a backend that runs its OWN admission (ReplicaPool raises
        # ShedError from submit) gets the same wire verdict
        class SheddingBackend(FakeBackend):
            def submit(self, model, image, deadline_ms=None):
                raise ShedError(model, "queue_full")

        tp, journal = make_transport(tmp_path, registry,
                                     backend=SheddingBackend())
        try:
            st, hdrs, payload = post(tp.port, "/v1/toy",
                                     {"image": an_image().tolist()})
            assert st == 503 and payload["reason"] == "queue_full"
            assert "retry-after" in hdrs
        finally:
            tp.close()
            journal.close()

    def test_replica_lost_is_503_retryable(self, tmp_path, registry):
        tp, journal = make_transport(
            tmp_path, registry,
            backend=FakeBackend(fail_with=ReplicaLost("p0 died")))
        try:
            st, hdrs, payload = post(tp.port, "/v1/toy",
                                     {"image": an_image().tolist()})
            assert st == 503 and payload["retryable"] is True
            assert "retry-after" in hdrs
            assert payload["error"] == "error"
        finally:
            tp.close()
            journal.close()

    def test_unknown_model_404_bad_body_400(self, tmp_path, registry):
        tp, journal = make_transport(tmp_path, registry)
        try:
            st, _, _ = post(tp.port, "/v1/nope",
                            {"image": an_image().tolist()})
            assert st == 404
            st, _, _ = post(tp.port, "/v1/toy", {"nope": 1})
            assert st == 400
            st, _, _ = post(tp.port, "/v1/toy", b"not json at all")
            assert st == 400
        finally:
            tp.close()
            journal.close()
        assert tp.ledger()["bad_request"] == 3 and tp.ledger()["balanced"]


class TestDeadline:
    def test_spent_budget_sheds_at_admission_backend_never_called(
            self, tmp_path, registry):
        backend = FakeBackend()
        tp, journal = make_transport(tmp_path, registry, backend=backend)
        try:
            st, _, payload = post(tp.port, "/v1/toy",
                                  {"image": an_image().tolist()},
                                  {DEADLINE_HEADER: "0.0001"})
            assert st == 504 and payload["stage"] == "admission"
            # shed means NOT EXECUTED: the backend never saw it
            assert backend.calls == []
        finally:
            tp.close()
            journal.close()
        assert tp.ledger()["deadline"] == 1

    def test_deadline_forwarded_to_backend(self, tmp_path, registry):
        backend = FakeBackend()
        tp, journal = make_transport(tmp_path, registry, backend=backend)
        try:
            st, _, _ = post(tp.port, "/v1/toy",
                            {"image": an_image().tolist()},
                            {DEADLINE_HEADER: "5000"})
            assert st == 200
            model, fwd = backend.calls[0]
            # the REMAINING budget rides to dispatch (shrunk by admission
            # overhead, never grown)
            assert fwd is not None and 0 < fwd <= 5000
        finally:
            tp.close()
            journal.close()

    def test_unparseable_deadline_header_is_400(self, tmp_path, registry):
        tp, journal = make_transport(tmp_path, registry)
        try:
            st, _, _ = post(tp.port, "/v1/toy",
                            {"image": an_image().tolist()},
                            {DEADLINE_HEADER: "soonish"})
            assert st == 400
        finally:
            tp.close()
            journal.close()

    def test_dispatch_pickup_past_deadline_sheds_504(self, tmp_path,
                                                     registry):
        # REAL router path: one request with a 5ms budget into a queue
        # whose max-wait is 80ms — the dispatcher picks it up past the
        # deadline and sheds it instead of executing (router counts it
        # an error; the wire sees 504 stage=dispatch)
        journal = RunJournal(os.path.join(str(tmp_path), "j.jsonl"),
                            kind="serve")
        eng = Engine(journal=journal, registry=registry)
        eng.register("toy", toy_fn, toy_variables(), input_shape=IMG,
                     buckets=(1, 2))
        eng.warmup()
        server = Server(eng, journal=journal, registry=registry,
                        max_wait_ms=80.0).start()
        tp = Transport(server, journal=journal, registry=registry).start()
        try:
            st, _, payload = post(tp.port, "/v1/toy",
                                  {"image": an_image().tolist()},
                                  {DEADLINE_HEADER: "5"})
            assert st == 504, payload
            assert payload["stage"] == "dispatch"
        finally:
            tp.close()
            server.drain("close")
            journal.close()
        assert tp.ledger()["deadline"] == 1
        evs = [e for e in read_journal(journal.path)
               if e.get("event") == "transport_request"]
        assert evs[0]["outcome"] == "deadline" and evs[0]["status"] == 504
        assert evs[0]["deadline_ms"] == 5.0


class TestTraceparent:
    def test_traceparent_rides_socket_into_journal_and_response(
            self, tmp_path, registry):
        backend = FakeBackend()
        tp, journal = make_transport(tmp_path, registry, backend=backend)
        ctx = propagate.new_trace()
        try:
            st, hdrs, _ = post(tp.port, "/v1/toy",
                               {"image": an_image().tolist()},
                               {"traceparent": ctx.to_traceparent()})
            assert st == 200
            # the response carries the server's span under the SAME trace
            echoed = propagate.from_traceparent(hdrs["traceparent"])
            assert echoed is not None
            assert echoed.trace_id == ctx.trace_id
            assert echoed.span_id != ctx.span_id
        finally:
            tp.close()
            journal.close()
        # the backend executed UNDER the propagated context...
        assert backend.ctxs[0] is not None
        assert backend.ctxs[0].trace_id == ctx.trace_id
        # ...and the journal event is linked to the caller's span
        evs = [e for e in read_journal(journal.path)
               if e.get("event") == "transport_request"]
        assert evs[0]["trace_id"] == ctx.trace_id
        assert evs[0]["parent_span_id"] == ctx.span_id

    def test_malformed_traceparent_starts_a_fresh_trace(self, tmp_path,
                                                        registry):
        tp, journal = make_transport(tmp_path, registry)
        try:
            st, hdrs, _ = post(tp.port, "/v1/toy",
                               {"image": an_image().tolist()},
                               {"traceparent": "00-garbage"})
            assert st == 200  # malformed context never fails a request
            assert propagate.from_traceparent(hdrs["traceparent"]) \
                is not None
        finally:
            tp.close()
            journal.close()


class TestTransportFaults:
    def teardown_method(self):
        faults.install(None)

    def test_torn_frame_fails_exactly_one_request(self, tmp_path,
                                                  registry):
        tp, journal = make_transport(tmp_path, registry)
        faults.install_spec("serve.transport:io_error@2", seed=3,
                            journal=journal, export_env=False)
        img = an_image().tolist()
        try:
            outcomes = []
            for _ in range(4):
                try:
                    st, _, _ = post(tp.port, "/v1/toy", {"image": img})
                    outcomes.append(st)
                except (http.client.HTTPException, OSError):
                    outcomes.append("torn")  # mid-frame reset: the
                    # connection dies without a response line
            assert outcomes == [200, "torn", 200, 200]
        finally:
            faults.install(None)
            tp.close()
            journal.close()
        led = tp.ledger()
        assert led["torn"] == 1 and led["ok"] == 3 and led["balanced"]
        evs = [e for e in read_journal(journal.path)
               if e.get("event") == "transport_request"
               and e.get("outcome") == "torn"]
        # status 0 = nothing hit the wire (check_journal allows it)
        assert len(evs) == 1 and evs[0]["status"] == 0

    def test_corrupt_frame_is_a_scoped_400(self, tmp_path, registry):
        tp, journal = make_transport(tmp_path, registry)
        faults.install_spec("serve.transport:corrupt@2", seed=3,
                            journal=journal, export_env=False)
        img = an_image().tolist()
        try:
            statuses = [post(tp.port, "/v1/toy", {"image": img})[0]
                        for _ in range(3)]
            assert statuses == [200, 400, 200]
        finally:
            faults.install(None)
            tp.close()
            journal.close()
        assert tp.ledger()["bad_request"] == 1

    def test_transport_is_a_registered_fault_point(self):
        assert "serve.transport" in faults.POINTS


class TestSchemaSync:
    def test_check_journal_knows_the_transport_schemas(self):
        from tools import check_journal as cj

        assert cj.EVENT_FIELDS["transport_request"] == (
            "status", "deadline_ms", "outcome")
        assert cj.EVENT_FIELDS["transport_server"] == (
            "host", "port", "outcome")
        assert cj.TRANSPORT_OUTCOMES == set(TRANSPORT_OUTCOMES)
        from deep_vision_tpu.serve.transport import \
            TRANSPORT_SERVER_OUTCOMES
        assert cj.TRANSPORT_SERVER_OUTCOMES == set(
            TRANSPORT_SERVER_OUTCOMES)

    def test_obs_report_without_transport_events_is_unchanged(self):
        from tools.obs_report import render, summarize_run

        events = [
            {"event": "run_manifest", "ts": 1.0, "run_id": "r",
             "kind": "serve", "argv": []},
            {"event": "serve_request", "ts": 2.0, "run_id": "r",
             "model": "toy", "latency_ms": 3.0, "outcome": "ok"},
            {"event": "exit", "ts": 3.0, "run_id": "r", "status": 0},
        ]
        summary = summarize_run(events)
        assert "fleet_edge" not in summary
        assert "fleet edge" not in render(summary)

    def test_obs_report_renders_the_fleet_edge(self):
        from tools.obs_report import render, summarize_run

        events = [
            {"event": "run_manifest", "ts": 1.0, "run_id": "r",
             "kind": "serve", "argv": []},
            {"event": "transport_server", "ts": 1.5, "run_id": "r",
             "host": "127.0.0.1", "port": 8080, "outcome": "started"},
            {"event": "transport_request", "ts": 2.0, "run_id": "r",
             "status": 200, "deadline_ms": 0.0, "outcome": "ok",
             "latency_ms": 3.0},
            {"event": "transport_request", "ts": 2.1, "run_id": "r",
             "status": 429, "deadline_ms": 0.0, "outcome": "shed",
             "latency_ms": 0.2, "reason": "rate_limited"},
            {"event": "transport_request", "ts": 2.2, "run_id": "r",
             "status": 504, "deadline_ms": 5.0, "outcome": "deadline",
             "latency_ms": 0.1, "stage": "dispatch"},
            {"event": "exit", "ts": 3.0, "run_id": "r", "status": 0},
        ]
        summary = summarize_run(events)
        edge = summary["fleet_edge"]
        assert edge["requests"]["by_status"] == {"200": 1, "429": 1,
                                                 "504": 1}
        assert edge["requests"]["balanced"] is True
        assert edge["deadline_stages"] == {"dispatch": 1}
        text = render(summary)
        assert "fleet edge" in text and "429x1" in text
        assert "deadline shed" in text and "dispatch=1" in text

    def test_knobs_registered(self):
        from deep_vision_tpu.core import knobs

        assert knobs.get_float("DVT_TRANSPORT_RETRY_AFTER_MS") > 0
        assert knobs.get_float("DVT_TRANSPORT_DEADLINE_MS") == 0.0


class TestHttpLoadClient:
    def test_client_honors_retry_after_and_recovers(self, tmp_path,
                                                    registry):
        from tools.loadgen import HttpLoadClient

        # shed twice, then admit: a retrying client must come back and
        # land the request, pacing itself by the server's Retry-After
        tp, journal = make_transport(
            tmp_path, registry,
            admission=StubAdmission(["rate_limited", "queue_full"]),
            retry_after_ms=30.0)
        client = HttpLoadClient("127.0.0.1", tp.port, registry=registry)
        try:
            row = client.submit("toy", an_image()).result(timeout=30)
            assert row["scores"] == [1.0, 2.0, 3.0]
        finally:
            client.close()
            tp.close()
            journal.close()
        assert client.counts["ok"] == 1
        assert client.counts["retries"] == 2
        assert client.counts["retry_after_honored"] >= 1
        led = tp.ledger()
        assert led["shed"] == 2 and led["ok"] == 1 and led["balanced"]

    def test_client_gives_up_typed_when_budget_exhausts(self, tmp_path,
                                                        registry):
        from deep_vision_tpu.resilience import RetryPolicy
        from tools.loadgen import HttpLoadClient

        tp, journal = make_transport(
            tmp_path, registry,
            admission=StubAdmission(["queue_full"] * 10),
            retry_after_ms=1.0)
        client = HttpLoadClient(
            "127.0.0.1", tp.port,
            retry=RetryPolicy(name="t", max_attempts=2, base_delay_s=0.001,
                              jitter=0.0, retry_on=(ShedError,)))
        try:
            with pytest.raises(ShedError):
                client.submit("toy", an_image()).result(timeout=30)
        finally:
            client.close()
            tp.close()
            journal.close()
        assert client.counts["shed"] == 1


class TestProcessFleet:
    """The real thing: spawned replica processes over real sockets."""

    def test_sigkill_respawn_zero_compiles_ledger_balances(
            self, tmp_path, registry):
        from tools.loadgen import fleet_builder

        work = str(tmp_path)
        journal = RunJournal(os.path.join(work, "journal.jsonl"),
                            kind="serve")
        pool = ProcReplicaPool(
            fleet_builder, replicas=2, run_dir=work,
            excache_dir=os.path.join(work, "excache"),
            journal=journal, registry=registry, heartbeat_s=0.4,
            ready_timeout_s=120.0)
        pool.start()
        try:
            # the parent's template paid the compiles and seeded the
            # cache; every CHILD warmed purely from it
            assert pool.template_warmup["backend_compiles"] > 0
            for rid, w in pool.warmup_stats().items():
                assert w["backend_compiles"] == 0, (rid, w)
                assert w["cache_hits"] == w["pairs"]

            img = an_image()
            for i in range(6):
                row = pool.submit("toy" if i % 2 else "aux",
                                  img).result(timeout=60)
            assert pool.ledger()["balanced"]

            # SIGKILL one replica with requests in flight: only ITS
            # in-flight window may fail, and the failures are typed
            victim = pool._slots["p0"]
            futs = [pool.submit("toy", img) for _ in range(8)]
            os.kill(victim.proc.pid, signal.SIGKILL)
            outcomes = {"ok": 0, "lost": 0}
            for fut in futs:
                try:
                    fut.result(timeout=60)
                    outcomes["ok"] += 1
                except ReplicaLost:
                    outcomes["lost"] += 1
            # the stream survived: the surviving replica answered its
            # share, and nothing failed UNTYPED
            assert outcomes["ok"] >= 1
            assert outcomes["ok"] + outcomes["lost"] == 8

            deadline = time.time() + 60
            while time.time() < deadline:
                if pool.replica_states()["p0"] == "serving" \
                        and victim.attempt == 2:
                    break
                time.sleep(0.1)
            assert victim.attempt == 2
            assert pool.replica_states()["p0"] == "serving"
            # rebirth was a disk read, not a compile
            assert pool.warmup_stats()["p0"]["backend_compiles"] == 0
            assert pool.submit("toy", img).result(timeout=60) is not None
        finally:
            summary = pool.drain("close")
            journal.close()
        assert summary["accepted"] == (summary["completed"]
                                       + summary["errors"]
                                       + summary["cancelled"])
        assert summary["pending"] == 0
        evs = read_journal(journal.path)
        losts = [e for e in evs if e.get("event") == "replica_lost"]
        recs = [e for e in evs if e.get("event") == "replica_recovered"]
        assert len(losts) == 1 and losts[0]["replica"] == "p0"
        assert len(recs) == 1 and recs[0]["attempt"] == 2
        # the excache counters IN THE JOURNAL: the respawned child's
        # warmup hit the cache for every pair and compiled nothing
        assert recs[0]["backend_compiles"] == 0
        assert recs[0]["cache_hits"] == recs[0]["pairs"] > 0

    def test_transport_fronts_the_process_fleet(self, tmp_path, registry):
        from tools.loadgen import fleet_builder

        work = str(tmp_path)
        journal = RunJournal(os.path.join(work, "journal.jsonl"),
                            kind="serve")
        pool = ProcReplicaPool(
            fleet_builder, replicas=2, run_dir=work,
            excache_dir=os.path.join(work, "excache"),
            journal=journal, registry=registry, heartbeat_s=0.4,
            ready_timeout_s=120.0)
        pool.start()
        tp = Transport(pool, journal=journal, registry=registry).start()
        ctx = propagate.new_trace()
        try:
            # one hop chain: client socket -> parent transport -> child
            # socket -> child transport, one trace end to end
            st, hdrs, payload = post(
                tp.port, "/v1/toy", {"image": an_image().tolist()},
                {"traceparent": ctx.to_traceparent(),
                 DEADLINE_HEADER: "30000"})
            assert st == 200 and "outputs" in payload
            st, health = get(tp.port, "/healthz")
            assert st == 200 and health["ok"] is True
            st, statusz = get(tp.port, "/statusz")
            assert st == 200
            assert statusz["telemetry_status"]["replicas"] == {
                "p0": "serving", "p1": "serving"}
        finally:
            tp.close()
            pool.drain("close")
            journal.close()
        assert tp.ledger()["ok"] == 1 and tp.ledger()["balanced"]
        # the trace crossed BOTH sockets: the parent's transport event
        # and the child's replica journal share the trace id
        evs = [e for e in read_journal(journal.path)
               if e.get("event") == "transport_request"]
        assert evs and evs[0]["trace_id"] == ctx.trace_id
        child_files = [p for p in os.listdir(work)
                       if p.startswith("replica-") and
                       p.endswith(".jsonl")]
        child_evs = []
        for p in child_files:
            child_evs += [e for e in read_journal(os.path.join(work, p))
                          if e.get("event") == "transport_request"]
        hops = [e for e in child_evs if e.get("trace_id") == ctx.trace_id]
        assert len(hops) == 1 and hops[0]["status"] == 200
