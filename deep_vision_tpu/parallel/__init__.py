from deep_vision_tpu.parallel.mesh import (
    MeshSpec,
    ShardingCoverageError,
    assert_sharding_coverage,
    create_mesh,
    data_sharding,
    replicated,
    shard_batch,
    sharding_coverage,
    local_mesh_devices,
)
from deep_vision_tpu.parallel.shardmap import (
    FAMILY_RULES,
    HeuristicRules,
    MOE_RULES,
    RESNET_RULES,
    VIT_RULES,
    ShardingRuleError,
    ShardingRules,
    get_rules,
    rules_for,
)
from deep_vision_tpu.parallel.moe import (
    expert_param_sharding,
    moe_ffn,
    moe_ffn_dense,
)
from deep_vision_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_param_sharding,
    stack_pipeline_params,
)
