"""Resilience primitives: retrying I/O + deterministic fault injection.

The layer that lets the trainer treat storage and transport as unreliable
by design (ROADMAP north star: survive production traffic, not just a
clean lab run):

- `retry`:  `RetryPolicy` — exponential backoff + jitter, deadline,
  retryable-exception classification; decorator / driver / attempt-loop
  forms; typed `retry` journal events and metrics counters. Shared by
  bench.py's rebuild-replay loop, the checkpoint sidecar writer, and
  shard opens in the tolerant record reader.
- `elastic`: the accelerator-layer arc — backend-failure classification
  (connection loss / dead-tunnel timeout / libtpu version skew),
  `BackendSupervisor` rebuild-replay choreography with typed
  `backend_lost`/`backend_recovered` journal events, cross-mesh
  checkpoint sharding metadata (restore a run saved on N devices onto
  M), and the threaded `backend_alive` liveness probe shared by bench
  and `tools/preflight.py`.
- `rendezvous`: the multi-HOST half of the elastic arc — file-backed
  generation-numbered membership (heartbeat leases, deadline-bounded
  barriers/consensus, join-time version handshake), `HostSupervisor`
  journaling typed `host_lost`/`host_joined`/`world_resized` events,
  and the bounded device fence that turns a peer SIGKILLed
  mid-collective into a typed error instead of an indefinite hang.
- `faults`: `FaultInjector` — seeded, deterministic faults driven by a
  `--fault-spec` string, with named injection points at every I/O
  boundary that cost one None-check when disabled. The mechanism behind
  `make chaos-smoke` and the crash-consistency tests.

Consumers of the skipping/quarantine behaviors these enable live next to
their data: the bad-record budget + dead-letter writer in
`data/records.py`, checkpoint quarantine in `core/checkpoint.py`.

jax-free at import (like obs/registry) so spawned data workers can use
both without dragging in a backend.
"""
from deep_vision_tpu.resilience.elastic import (
    BACKEND_LOST_KINDS,
    BackendSupervisor,
    backend_alive,
    classify_backend_error,
    replace_on_mesh,
    sharding_meta,
)
from deep_vision_tpu.resilience.faults import (
    ENV_SEED,
    ENV_SPEC,
    FaultInjected,
    FaultInjector,
    FaultSpecError,
    fire,
    install,
    install_spec,
    installed,
    transform,
)
from deep_vision_tpu.resilience.rendezvous import (
    HostLostError,
    HostSupervisor,
    Rendezvous,
    RendezvousError,
    RendezvousRefused,
    RendezvousTimeout,
    WorldResized,
    WorldView,
)
from deep_vision_tpu.resilience.retry import DEFAULT_RETRY_ON, RetryPolicy

__all__ = [
    "HostLostError",
    "HostSupervisor",
    "Rendezvous",
    "RendezvousError",
    "RendezvousRefused",
    "RendezvousTimeout",
    "WorldResized",
    "WorldView",
    "BACKEND_LOST_KINDS",
    "BackendSupervisor",
    "DEFAULT_RETRY_ON",
    "ENV_SEED",
    "ENV_SPEC",
    "FaultInjected",
    "FaultInjector",
    "FaultSpecError",
    "RetryPolicy",
    "backend_alive",
    "classify_backend_error",
    "fire",
    "install",
    "install_spec",
    "installed",
    "replace_on_mesh",
    "sharding_meta",
    "transform",
]
