"""Ring attention + multihost helpers on the 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.parallel.mesh import create_mesh, data_sharding
from deep_vision_tpu.parallel.ring_attention import (
    dense_attention,
    ring_attention,
)
from deep_vision_tpu.parallel import multihost

pytestmark = pytest.mark.slow  # jit-heavy: excluded from the fast tier (`-m "not slow"`)


def _qkv(b=2, t=32, h=4, d=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(mesh8, causal):
    q, k, v = _qkv()
    expected = dense_attention(q, k, v, causal=causal)
    sharding = data_sharding(mesh8, 4)
    # seq axis sharded over all 8 devices: 32 -> 4 per device
    spec = jax.sharding.NamedSharding(
        mesh8, jax.sharding.PartitionSpec(None, "data", None, None)
    )
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    got = ring_attention(qs, ks, vs, mesh8, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_grads_flow(mesh8):
    q, k, v = _qkv(b=1, t=16, h=2, d=8)
    spec = jax.sharding.NamedSharding(
        mesh8, jax.sharding.PartitionSpec(None, "data", None, None)
    )

    def loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh8, causal=True) ** 2)

    g = jax.grad(loss)(jax.device_put(q, spec), jax.device_put(k, spec),
                       jax.device_put(v, spec))
    gd = jax.grad(lambda q, k, v: jnp.sum(dense_attention(q, k, v, causal=True) ** 2))(
        q, k, v
    )
    np.testing.assert_allclose(np.asarray(g), np.asarray(gd), rtol=2e-3, atol=1e-4)
    assert np.isfinite(np.asarray(g)).all()


def test_ring_attention_under_jit(mesh8):
    q, k, v = _qkv(b=1, t=16, h=2, d=8)
    spec = jax.sharding.NamedSharding(
        mesh8, jax.sharding.PartitionSpec(None, "data", None, None)
    )
    f = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh8, causal=False))
    got = f(jax.device_put(q, spec), jax.device_put(k, spec), jax.device_put(v, spec))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(dense_attention(q, k, v)), rtol=2e-4, atol=2e-5
    )


def test_per_host_batch_size_divisibility(monkeypatch):
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    assert multihost.per_host_batch_size(64) == 16
    with pytest.raises(ValueError):
        multihost.per_host_batch_size(66)


def test_ring_attention_very_negative_scores(mesh8):
    # regression: rows whose real scores are all far below zero must not be
    # flattened by a 0-clamped running max in the online-softmax merge
    q, k, v = _qkv(b=1, t=16, h=1, d=8, seed=3)
    q = q * 120.0  # scores ~ N(0, ~120): rows with max < -87 underflow
    # exp(s - 0) in fp32, so a 0-clamped running max would zero them out
    spec = jax.sharding.NamedSharding(
        mesh8, jax.sharding.PartitionSpec(None, "data", None, None)
    )
    got = ring_attention(
        jax.device_put(q, spec), jax.device_put(k, spec),
        jax.device_put(v, spec), mesh8, causal=True,
    )
    expected = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_multihost_single_process_helpers(mesh8):
    # single-process semantics of every helper (multi-process needs a cluster)
    multihost.initialize_distributed()  # no-op without env
    assert multihost.process_count() == 1
    assert multihost.is_primary()
    assert multihost.host_shard() == (0, 1)
    assert multihost.per_host_batch_size(64) == 64
    multihost.sync_hosts()
    batch = {"x": np.arange(16, dtype=np.float32).reshape(16, 1)}
    arr = multihost.form_global_array(batch, mesh8)
    assert arr["x"].shape == (16, 1)
    np.testing.assert_allclose(np.asarray(arr["x"]), batch["x"])


class TestRingFlash:
    """Flash-kernel ring body (interpret mode on the CPU mesh) vs dense."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, mesh8, causal):
        import numpy as np
        from deep_vision_tpu.parallel.ring_attention import (
            dense_attention,
            ring_attention,
        )
        from jax.sharding import NamedSharding, PartitionSpec as P

        rng = np.random.RandomState(0)
        t = 8 * 16  # 16 per shard on the 8-device mesh
        qn, kn, vn = (rng.randn(2, t, 2, 8).astype(np.float32)
                      for _ in range(3))
        spec = NamedSharding(mesh8, P(None, "data", None, None))
        args = [jax.device_put(x, spec) for x in (qn, kn, vn)]
        out = ring_attention(*args, mesh8, causal=causal, use_flash=True)
        ref = dense_attention(jnp.asarray(qn), jnp.asarray(kn),
                              jnp.asarray(vn), causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_grads_match_dense(self, mesh8):
        import numpy as np
        from deep_vision_tpu.parallel.ring_attention import (
            dense_attention,
            ring_attention,
        )
        from jax.sharding import NamedSharding, PartitionSpec as P

        rng = np.random.RandomState(1)
        t = 8 * 16
        qn, kn, vn = (rng.randn(1, t, 2, 8).astype(np.float32)
                      for _ in range(3))
        spec = NamedSharding(mesh8, P(None, "data", None, None))

        def f_ring(q, k, v):
            return jnp.sum(
                ring_attention(q, k, v, mesh8, causal=True, use_flash=True) ** 2
            )

        def f_dense(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

        args = [jax.device_put(x, spec) for x in (qn, kn, vn)]
        g1 = jax.grad(f_ring, argnums=(0, 1, 2))(*args)
        g2 = jax.grad(f_dense, argnums=(0, 1, 2))(
            jnp.asarray(qn), jnp.asarray(kn), jnp.asarray(vn))
        for a, b, name in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-4, err_msg=name)
