"""Pipeline (GPipe) and expert (MoE) parallelism on the 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.parallel.mesh import create_mesh

pytestmark = pytest.mark.slow  # jit-heavy: excluded from the fast tier (`-m "not slow"`)
from deep_vision_tpu.parallel.moe import (
    expert_param_sharding,
    moe_ffn,
    moe_ffn_dense,
)
from deep_vision_tpu.parallel.pipeline import (

    pipeline_apply,
    pipeline_param_sharding,
    stack_pipeline_params,
)


def _stage_params(n_stages, d=16, h=32, seed=0):
    rng = np.random.RandomState(seed)
    return [
        {
            "w1": jnp.asarray(rng.randn(d, h) * 0.1, jnp.float32),
            "w2": jnp.asarray(rng.randn(h, d) * 0.1, jnp.float32),
        }
        for _ in range(n_stages)
    ]


def _stage_fn(p, x):
    return x + jnp.tanh(x @ p["w1"]) @ p["w2"]


class TestPipeline:
    def _mesh(self):
        # 4-stage pipeline over the model axis, DP over the rest
        return create_mesh(data=2, model=4)

    def test_forward_matches_sequential(self):
        mesh = self._mesh()
        params_list = _stage_params(4)
        stacked = stack_pipeline_params(params_list)
        stacked = jax.device_put(stacked, pipeline_param_sharding(mesh, stacked))
        x = jnp.asarray(np.random.RandomState(1).randn(8, 16), jnp.float32)
        out = pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=4)
        ref = x
        for p in params_list:
            ref = _stage_fn(p, ref)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grads_match_sequential(self):
        mesh = self._mesh()
        params_list = _stage_params(4, seed=2)
        stacked = stack_pipeline_params(params_list)
        stacked = jax.device_put(stacked, pipeline_param_sharding(mesh, stacked))
        x = jnp.asarray(np.random.RandomState(3).randn(8, 16), jnp.float32)

        def loss_pipe(sp):
            return jnp.sum(
                pipeline_apply(_stage_fn, sp, x, mesh, num_microbatches=2) ** 2
            )

        def loss_ref(plist):
            h = x
            for p in plist:
                h = _stage_fn(p, h)
            return jnp.sum(h**2)

        g_pipe = jax.tree_util.tree_leaves(jax.grad(loss_pipe)(stacked))
        g_ref = jax.tree_util.tree_leaves(
            stack_pipeline_params(jax.grad(loss_ref)(params_list))
        )
        for a, b in zip(g_pipe, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_microbatch_count_one_and_equal_to_batch(self):
        mesh = self._mesh()
        params_list = _stage_params(4, seed=4)
        stacked = stack_pipeline_params(params_list)
        stacked = jax.device_put(stacked, pipeline_param_sharding(mesh, stacked))
        x = jnp.asarray(np.random.RandomState(5).randn(8, 16), jnp.float32)
        ref = x
        for p in params_list:
            ref = _stage_fn(p, ref)
        for m in (1, 8):
            out = pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=m)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=1e-5, atol=1e-5)

    def test_stage_count_mismatch_raises(self):
        mesh = self._mesh()
        stacked = stack_pipeline_params(_stage_params(3))
        x = jnp.zeros((8, 16), jnp.float32)
        with pytest.raises(ValueError, match="pipeline stages"):
            pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=2)


def _moe_fixture(e=8, d=16, h=32, t=32, seed=0):
    rng = np.random.RandomState(seed)
    router_w = jnp.asarray(rng.randn(d, e) * 0.5, jnp.float32)
    ep = {
        "w1": jnp.asarray(rng.randn(e, d, h) * 0.1, jnp.float32),
        "b1": jnp.zeros((e, h), jnp.float32),
        "w2": jnp.asarray(rng.randn(e, h, d) * 0.1, jnp.float32),
        "b2": jnp.zeros((e, d), jnp.float32),
    }
    x = jnp.asarray(rng.randn(t, d), jnp.float32)
    return router_w, ep, x


class TestMoe:
    def test_matches_dense_when_capacity_suffices(self, mesh8):
        router_w, ep, x = _moe_fixture()
        ep_sh = jax.device_put(ep, expert_param_sharding(mesh8, ep))
        # T_loc = 4 per device: capacity 4 can never overflow
        out = moe_ffn(router_w, ep_sh, x, mesh8, capacity=4)
        ref = moe_ffn_dense(router_w, ep, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_expert_grads_match_dense(self, mesh8):
        router_w, ep, x = _moe_fixture(seed=1)
        ep_sh = jax.device_put(ep, expert_param_sharding(mesh8, ep))

        def lp(e_):
            return jnp.sum(moe_ffn(router_w, e_, x, mesh8, capacity=4) ** 2)

        def lr(e_):
            return jnp.sum(moe_ffn_dense(router_w, e_, x) ** 2)

        gp = jax.tree_util.tree_leaves(jax.grad(lp)(ep_sh))
        gr = jax.tree_util.tree_leaves(jax.grad(lr)(ep))
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6)

    def test_capacity_drop_is_zero_not_nan(self, mesh8):
        router_w, ep, x = _moe_fixture(seed=2)
        ep_sh = jax.device_put(ep, expert_param_sharding(mesh8, ep))
        out = moe_ffn(router_w, ep_sh, x, mesh8, capacity=1)
        arr = np.asarray(out)
        assert np.isfinite(arr).all()
        # with capacity 1 and 4 tokens/device, some tokens must be dropped
        # (routed rows through a 2-layer MLP with bias 0 are ~never exactly 0)
        assert (np.abs(arr).sum(axis=-1) == 0).any()

    def test_load_balancing_loss_uniform_is_one(self):
        from deep_vision_tpu.parallel.moe import load_balancing_loss

        e = 4
        # perfectly uniform routing: every expert equally probable AND
        # equally chosen -> loss hits its minimum of exactly 1
        gates = jnp.tile(jnp.full((1, e), 1.0 / e), (8, 1))
        # break argmax ties deterministically across experts
        gates = gates + jnp.eye(e)[jnp.arange(8) % e] * 1e-6
        gates = gates / gates.sum(-1, keepdims=True)
        assert abs(float(load_balancing_loss(gates)) - 1.0) < 1e-4
        # collapsed routing: all tokens on one expert -> loss ~ E
        collapsed = jnp.tile(
            jax.nn.softmax(jnp.array([[10.0, 0, 0, 0]])), (8, 1)
        )
        assert float(load_balancing_loss(collapsed)) > 3.0

    def test_experts_not_divisible_raises(self, mesh8):
        router_w, ep, x = _moe_fixture(e=6, seed=3)
        with pytest.raises(ValueError, match="divisible"):
            moe_ffn(router_w, ep, x, mesh8, capacity=4)

    def test_bf16_routing_matches_f32_expert_choice(self, mesh8):
        """Router runs in f32 even for bf16 activations (ADVICE r2): the
        expert-parallel path and the dense in-model path must pick the SAME
        experts, or a vmoe checkpoint deploys differently via moe_ffn."""
        router_w, ep, x = _moe_fixture(seed=7)
        xb = x.astype(jnp.bfloat16)
        ep_b = jax.tree_util.tree_map(lambda p: p.astype(jnp.bfloat16), ep)
        ep_sh = jax.device_put(ep_b, expert_param_sharding(mesh8, ep_b))
        out = moe_ffn(router_w, ep_sh, xb, mesh8, capacity=32)
        ref = moe_ffn_dense(router_w, ep_b, xb)
        assert out.dtype == jnp.bfloat16
        # identical expert selection => differences are bf16 rounding only;
        # a routing mismatch would swap whole expert outputs (O(1) error)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=0.1, atol=0.05,
        )
