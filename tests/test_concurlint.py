"""concurlint (lint/concur.py DV101-DV104) + locksmith (obs/locksmith.py):
per-rule positive/negative fixtures, suppression/baseline interplay, the
repo self-lint gate, and the runtime sanitizer's unit contracts (forced
inversion detected, disabled-mode overhead, clean serve drain journals
zero violations).
"""
from __future__ import annotations

import json
import pickle
import textwrap
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from deep_vision_tpu.lint import lint_source
from deep_vision_tpu.lint.__main__ import main as lint_main
from deep_vision_tpu.lint.rules import RULES
from deep_vision_tpu.obs import RunJournal, locksmith, read_journal
from deep_vision_tpu.obs.registry import Registry

REPO_ROOT = Path(__file__).resolve().parents[1]


def run(src: str, **kw):
    kept, _ = lint_source(textwrap.dedent(src), "fixture.py", **kw)
    return kept


def codes(src: str, **kw):
    return [f.code for f in run(src, **kw)]


@pytest.fixture(autouse=True)
def _disarm_locksmith():
    yield
    locksmith.disarm()


# -- DV101 shared-mutable-state ----------------------------------------------

class TestDV101:
    def test_unguarded_thread_shared_write_flags(self):
        found = run("""
            import threading

            class Worker:
                def __init__(self):
                    self.count = 0
                    self._t = threading.Thread(target=self._loop)

                def _loop(self):
                    self.count += 1

                def reset(self):
                    self.count = 0
        """, select=["DV101"])
        assert [f.code for f in found] == ["DV101"]
        assert "self.count" in found[0].message
        assert "_loop" in found[0].message and "reset" in found[0].message

    def test_executor_submit_target_flags(self):
        assert codes("""
            class Pool:
                def __init__(self, ex):
                    self.done = 0
                    ex.submit(self._work)

                def _work(self):
                    self.done = 1

                def clear(self):
                    self.done = 0
        """, select=["DV101"]) == ["DV101"]

    def test_common_guard_is_clean(self):
        assert run("""
            import threading

            class Worker:
                def __init__(self):
                    self.count = 0
                    self._lock = threading.Lock()
                    self._t = threading.Thread(target=self._loop)

                def _loop(self):
                    with self._lock:
                        self.count += 1

                def reset(self):
                    with self._lock:
                        self.count = 0
        """, select=["DV101"]) == []

    def test_disjoint_guards_flag(self):
        # both sides hold A lock — just not the SAME lock: still a race
        assert codes("""
            import threading

            class Worker:
                def __init__(self):
                    self.count = 0
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                    self._t = threading.Thread(target=self._loop)

                def _loop(self):
                    with self._a:
                        self.count += 1

                def reset(self):
                    with self._b:
                        self.count = 0
        """, select=["DV101"]) == ["DV101"]

    def test_init_writes_do_not_count(self):
        # construction happens-before thread start: __init__ is exempt
        assert run("""
            import threading

            class Worker:
                def __init__(self):
                    self.count = 0
                    self._t = threading.Thread(target=self._loop)

                def _loop(self):
                    self.count += 1
        """, select=["DV101"]) == []

    def test_transitive_thread_reach(self):
        # the thread target delegates to a helper; the helper's write is
        # still in the thread domain
        assert codes("""
            import threading

            class Worker:
                def __init__(self):
                    self.state = None
                    self._t = threading.Thread(target=self._loop)

                def _loop(self):
                    self._step()

                def _step(self):
                    self.state = "running"

                def reset(self):
                    self.state = None
        """, select=["DV101"]) == ["DV101"]

    def test_locksmith_factory_recognized_as_lock(self):
        assert run("""
            from deep_vision_tpu.obs import locksmith
            import threading

            class Worker:
                def __init__(self):
                    self.count = 0
                    self._lock = locksmith.lock("w")
                    self._t = threading.Thread(target=self._loop)

                def _loop(self):
                    with self._lock:
                        self.count += 1

                def reset(self):
                    with self._lock:
                        self.count = 0
        """, select=["DV101"]) == []

    def test_callback_attribute_target_out_of_scope(self):
        # pool.submit(self.transform): `transform` is a user-supplied
        # callable attribute, not a method of the class — not our domain
        assert run("""
            class Loader:
                def __init__(self, transform, pool):
                    self.transform = transform
                    self.n = 0
                    pool.submit(self.transform)

                def bump(self):
                    self.n += 1
        """, select=["DV101"]) == []


# -- DV102 lock-order inversion ----------------------------------------------

class TestDV102:
    def test_module_lock_inversion_flags(self):
        found = run("""
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def f():
                with A:
                    with B:
                        pass

            def g():
                with B:
                    with A:
                        pass
        """, select=["DV102"])
        assert [f.code for f in found] == ["DV102"]
        assert "inversion" in found[0].message
        assert "A" in found[0].message and "B" in found[0].message

    def test_consistent_order_clean(self):
        assert run("""
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def f():
                with A:
                    with B:
                        pass

            def g():
                with A:
                    with B:
                        pass
        """, select=["DV102"]) == []

    def test_multi_item_with_counts_as_nesting(self):
        assert codes("""
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def f():
                with A, B:
                    pass

            def g():
                with B, A:
                    pass
        """, select=["DV102"]) == ["DV102"]

    def test_inversion_across_call_edge(self):
        # f holds _a and calls helper() which takes _b; g takes them in
        # the reverse order — the cycle only exists across the call edge
        assert codes("""
            import threading

            class S:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def f(self):
                    with self._a:
                        self.helper()

                def helper(self):
                    with self._b:
                        pass

                def g(self):
                    with self._b:
                        with self._a:
                            pass
        """, select=["DV102"]) == ["DV102"]

    def test_nested_same_nonreentrant_lock_flags(self):
        found = run("""
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()

                def outer(self):
                    with self._lock:
                        with self._lock:
                            pass
        """, select=["DV102"])
        assert [f.code for f in found] == ["DV102"]
        assert "non-reentrant" in found[0].message

    def test_nested_rlock_clean(self):
        assert run("""
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.RLock()

                def outer(self):
                    with self._lock:
                        with self._lock:
                            pass
        """, select=["DV102"]) == []

    def test_nested_same_lock_via_call_edge_flags(self):
        # the PR 5 bug shape: a method that holds the lock calls another
        # method that re-acquires it
        assert codes("""
            import threading

            class J:
                def __init__(self):
                    self._lock = threading.Lock()

                def write(self):
                    with self._lock:
                        pass

                def dump(self):
                    with self._lock:
                        self.write()
        """, select=["DV102"]) == ["DV102"]

    def test_unrelated_with_blocks_ignored(self):
        assert run("""
            import threading

            A = threading.Lock()

            def f(path):
                with open(path) as fh:
                    with A:
                        return fh.read()
        """, select=["DV102"]) == []


# -- DV103 signal-unsafe handler ---------------------------------------------

class TestDV103:
    def test_lock_in_handler_flags(self):
        found = run("""
            import signal
            import threading

            _LOCK = threading.Lock()

            def handler(signum, frame):
                with _LOCK:
                    pass

            signal.signal(signal.SIGTERM, handler)
        """, select=["DV103"])
        assert [f.code for f in found] == ["DV103"]
        assert "self-deadlock" in found[0].message

    def test_blocking_calls_reachable_from_method_handler(self):
        # the exact PR 5 incident: the SIGTERM handler dumps a flight
        # bundle (journal + recorder locks) in signal context
        found = run("""
            import signal

            class Guard:
                def install(self):
                    signal.signal(signal.SIGTERM, self._on_sigterm)

                def _on_sigterm(self, signum, frame):
                    self._drain()

                def _drain(self):
                    from deep_vision_tpu.obs import flight
                    flight.emergency_dump("preempt")
        """, select=["DV103"])
        assert [f.code for f in found] == ["DV103"]
        assert "flight" in found[0].message

    def test_future_result_and_journal_write_flag(self):
        found = run("""
            import signal

            class S:
                def install(self):
                    signal.signal(signal.SIGTERM, self._on_term)

                def _on_term(self, signum, frame):
                    self.pending.result()
                    self.journal.write("exit", status="sigterm")
        """, select=["DV103"])
        assert [f.code for f in found] == ["DV103", "DV103"]

    def test_flag_then_daemon_thread_is_clean(self):
        # the sanctioned fix shape (parallel/multihost.PreemptionGuard):
        # set a flag, hand the blocking work to a thread — target=
        # references are not signal-context calls
        assert run("""
            import signal
            import threading

            class Guard:
                def install(self):
                    signal.signal(signal.SIGTERM, self._on_sigterm)

                def _on_sigterm(self, signum, frame):
                    self.requested = True
                    threading.Thread(target=self._dump, daemon=True).start()

                def _dump(self):
                    from deep_vision_tpu.obs import flight
                    flight.emergency_dump("preempt")
        """, select=["DV103"]) == []

    def test_event_set_is_clean(self):
        # serve/router.py's handler: Event.set never blocks
        assert run("""
            import signal
            import threading

            class Server:
                def __init__(self):
                    self._stop = threading.Event()

                def install_sigterm(self):
                    signal.signal(signal.SIGTERM, self._on_sigterm)

                def _on_sigterm(self, signum, frame):
                    self._stop.set()
        """, select=["DV103"]) == []

    def test_str_join_not_a_thread_join(self):
        assert run("""
            import signal

            def handler(signum, frame):
                print(", ".join(["a", "b"]))

            signal.signal(signal.SIGTERM, handler)
        """, select=["DV103"]) == []

    def test_queue_ops_in_handler_flag(self):
        assert codes("""
            import queue
            import signal

            class S:
                def __init__(self):
                    self._q = queue.Queue()
                    signal.signal(signal.SIGTERM, self._on_term)

                def _on_term(self, signum, frame):
                    self._q.put(None)
        """, select=["DV103"]) == ["DV103"]


# -- DV104 future-protocol misuse --------------------------------------------

class TestDV104:
    def test_set_result_without_notify_flags(self):
        found = run("""
            def resolve(req, row):
                req.future.set_result(row)
        """, select=["DV104"])
        assert [f.code for f in found] == ["DV104"]
        assert "InvalidStateError" in found[0].message

    def test_set_exception_without_notify_flags(self):
        assert codes("""
            def fail(req, exc):
                req.future.set_exception(exc)
        """, select=["DV104"]) == ["DV104"]

    def test_notify_in_scope_is_clean(self):
        # the PR 6 fix shape (serve/router._fail_request)
        assert run("""
            def fail(req, exc):
                if not req.future.set_running_or_notify_cancel():
                    return
                req.future.set_exception(exc)
        """, select=["DV104"]) == []

    def test_locally_created_future_is_clean(self):
        # a promise the scope owns: nobody can have cancelled it yet
        assert run("""
            from concurrent.futures import Future

            def make():
                f = Future()
                f.set_result(1)
                return f
        """, select=["DV104"]) == []


# -- suppression + baseline interplay ----------------------------------------

DV101_SRC = """
import threading

class Worker:
    def __init__(self):
        self.count = 0
        self._t = threading.Thread(target=self._loop)

    def _loop(self):
        self.count += 1{pragma}

    def reset(self):
        self.count = 0
"""


def test_dv1xx_inline_suppression():
    dirty = textwrap.dedent(DV101_SRC.format(pragma=""))
    kept, dropped = lint_source(dirty, "mod.py", select=["DV101"])
    assert [f.code for f in kept] == ["DV101"]
    clean = textwrap.dedent(DV101_SRC.format(
        pragma="  # jaxlint: disable=DV101 -- test-only counter"))
    kept, dropped = lint_source(clean, "mod.py", select=["DV101"])
    assert kept == []
    assert [f.code for f in dropped] == ["DV101"]


def test_dv1xx_baseline_interplay(tmp_path, capsys):
    """A baselined DV101 finding is accepted; a second identical one (or
    a drifted line) still matches on (code, path, symbol, message)."""
    mod = tmp_path / "mod.py"
    mod.write_text(textwrap.dedent(DV101_SRC.format(pragma="")))
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [tool.jaxlint]
        paths = ["mod.py"]
        baseline = "baseline.json"
    """))
    pp = str(tmp_path / "pyproject.toml")
    assert lint_main(["--config", pp]) == 1
    capsys.readouterr()
    # accept into the baseline, then the same tree is clean
    assert lint_main(["--config", pp, "--write-baseline"]) == 0
    capsys.readouterr()
    assert lint_main(["--config", pp]) == 0
    # line drift must not resurrect the accepted finding
    mod.write_text("# a new leading comment\n" + mod.read_text())
    assert lint_main(["--config", pp]) == 0


def test_dv1xx_rules_registered():
    for code in ("DV101", "DV102", "DV103", "DV104", "DV007"):
        assert code in RULES
        name, severity, check, doc = RULES[code]
        assert severity in ("error", "warning") and callable(check)


def test_repo_self_lint_concur_clean(capsys):
    """The shipped tree is clean under the concurrency pack specifically
    (true positives fixed, not baselined — the committed baseline stays
    empty). This is the acceptance gate for DV101-DV104 + DV007."""
    rc = lint_main(["--config", str(REPO_ROOT / "pyproject.toml"),
                    "--select", "DV101,DV102,DV103,DV104,DV007"])
    out = capsys.readouterr().out
    assert rc == 0, f"concurlint found new violations:\n{out}"
    baseline = json.loads(
        (REPO_ROOT / ".jaxlint-baseline.json").read_text())
    assert baseline["findings"] == [], "the committed baseline must stay empty"


def test_concur_gate_catches_injected_violation(tmp_path, capsys):
    bad = tmp_path / "bad_threads.py"
    bad.write_text(textwrap.dedent(DV101_SRC.format(pragma="")))
    rc = lint_main([str(bad),
                    "--config", str(REPO_ROOT / "pyproject.toml")])
    capsys.readouterr()
    assert rc == 1


# -- locksmith: runtime sanitizer ---------------------------------------------

class TestLocksmith:
    def test_forced_inversion_detected_and_journaled(self, tmp_path):
        jp = tmp_path / "locks.jsonl"
        journal = RunJournal(str(jp))
        journal.manifest()
        san = locksmith.arm(journal=journal, registry=Registry())
        a = locksmith.lock("test.A")
        b = locksmith.lock("test.B")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        v = san.violations()
        assert len(v) == 1
        assert {v[0]["lock_a"], v[0]["lock_b"]} == {"test.A", "test.B"}
        assert v[0]["stack"] and v[0]["prior_stack"]
        locksmith.disarm()
        journal.close()
        events = read_journal(str(jp))
        viol = [e for e in events if e["event"] == "lock_order_violation"]
        assert len(viol) == 1
        assert viol[0]["lock_a"] and viol[0]["lock_b"]
        from tools.check_journal import check_journal

        assert check_journal(str(jp), strict=True) == []

    def test_inversion_detected_across_threads(self):
        san = locksmith.arm(registry=Registry())
        a = locksmith.lock("thr.A")
        b = locksmith.lock("thr.B")
        first_done = threading.Event()

        def path_ab():
            with a:
                with b:
                    pass
            first_done.set()

        def path_ba():
            first_done.wait(5)  # sequenced: detection, not a real deadlock
            with b:
                with a:
                    pass

        t1 = threading.Thread(target=path_ab)
        t2 = threading.Thread(target=path_ba)
        t1.start(); t2.start()
        t1.join(5); t2.join(5)
        v = san.violations()
        assert len(v) == 1
        assert v[0]["thread"] != v[0]["prior_thread"]

    def test_violation_latched_per_pair(self):
        san = locksmith.arm(registry=Registry())
        a = locksmith.lock("latch.A")
        b = locksmith.lock("latch.B")
        with a:
            with b:
                pass
        for _ in range(3):
            with b:
                with a:
                    pass
        assert len(san.violations()) == 1

    def test_consistent_order_clean(self):
        san = locksmith.arm(registry=Registry())
        a = locksmith.lock("ok.A")
        b = locksmith.lock("ok.B")
        for _ in range(5):
            with a:
                with b:
                    pass
        assert san.violations() == []

    def test_hold_contention_event(self, tmp_path):
        jp = tmp_path / "hold.jsonl"
        journal = RunJournal(str(jp))
        san = locksmith.arm(journal=journal, registry=Registry(),
                            hold_ms=1.0)
        lk = locksmith.lock("slow.lock")
        with lk:
            time.sleep(0.02)
        rep = san.report()
        assert rep["locks"]["slow.lock"]["hold_contentions"] == 1
        assert rep["max_hold_lock"] == "slow.lock"
        assert rep["max_hold_ms"] >= 10.0
        locksmith.disarm()
        journal.close()
        cont = [e for e in read_journal(str(jp))
                if e["event"] == "lock_contention"]
        assert len(cont) == 1 and cont[0]["kind"] == "hold"
        assert cont[0]["lock"] == "slow.lock" and cont[0]["ms"] >= 10.0

    def test_wait_contention_event(self):
        san = locksmith.arm(registry=Registry(), wait_ms=5.0)
        lk = locksmith.lock("contended.lock")
        holding = threading.Event()

        def holder():
            with lk:
                holding.set()
                time.sleep(0.05)

        t = threading.Thread(target=holder)
        t.start()
        holding.wait(5)
        with lk:  # blocks ~50ms on the holder
            pass
        t.join(5)
        rep = san.report()
        assert rep["locks"]["contended.lock"]["wait_contentions"] >= 1
        assert rep["top_contended"] == "contended.lock"

    def test_condition_wait_releases_hold(self):
        # a dispatcher parked on an empty queue is not a marathon hold
        san = locksmith.arm(registry=Registry(), hold_ms=10.0)
        cv = locksmith.condition("park.cv")

        def waiter():
            with cv:
                cv.wait(timeout=0.1)

        t = threading.Thread(target=waiter)
        t.start()
        t.join(5)
        rep = san.report()
        assert rep["locks"]["park.cv"]["hold_contentions"] == 0

    def test_condition_notify_roundtrip(self):
        locksmith.arm(registry=Registry())
        cv = locksmith.condition("rt.cv")
        got = []

        def consumer():
            with cv:
                while not got:
                    cv.wait(timeout=1.0)

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.02)
        with cv:
            got.append(1)
            cv.notify_all()
        t.join(5)
        assert not t.is_alive()

    def test_reentrant_same_name_no_self_violation(self):
        san = locksmith.arm(registry=Registry())
        lk = locksmith.rlock("re.lock")
        with lk:
            with lk:
                pass
        assert san.violations() == []

    def test_disabled_overhead_probe(self):
        """Disabled-mode cost: one module-global load + None check per
        op on top of the raw primitive (the faults.fire / flight.note
        budget; chaos-smoke enforces 2us, this a looser CI bound)."""
        assert locksmith.get_sanitizer() is None
        lk = locksmith.lock("idle.lock")
        n = 50_000
        t0 = time.perf_counter()
        for _ in range(n):
            with lk:
                pass
        ns = (time.perf_counter() - t0) / n * 1e9
        assert ns < 20_000, f"disabled lock cycle cost {ns:.0f}ns"

    def test_instrumented_lock_pickles(self):
        lk = locksmith.lock("pickle.lock")
        clone = pickle.loads(pickle.dumps(lk))
        assert clone.name == "pickle.lock"
        with clone:
            assert clone.locked()
        assert not clone.locked()

    def test_rlock_pickle_keeps_reentrancy(self):
        # regression: an rlock that unpickled as a plain Lock would
        # self-deadlock in the worker on the first nested acquire
        clone = pickle.loads(pickle.dumps(locksmith.rlock("pickle.rlock")))
        with clone:
            with clone:  # must not deadlock
                pass

    def test_arm_from_env(self, monkeypatch):
        monkeypatch.delenv(locksmith.ENV_ARM, raising=False)
        assert locksmith.arm_from_env() is None
        assert locksmith.get_sanitizer() is None
        monkeypatch.setenv(locksmith.ENV_ARM, "1")
        monkeypatch.setenv(locksmith.ENV_HOLD_MS, "123.0")
        san = locksmith.arm_from_env()
        assert san is not None and locksmith.get_sanitizer() is san
        assert san.hold_ms == 123.0

    def test_report_disarmed_placeholder(self):
        assert locksmith.get_sanitizer() is None
        rep = locksmith.report()
        assert rep["armed"] is False and rep["violations"] == []


# -- locksmith x serve: a clean drain journals zero violations ----------------

def _toy_fn(variables, images):
    flat = images.reshape((images.shape[0], -1))
    return {"scores": flat @ variables["w"],
            "mean": images.mean(axis=(1, 2, 3))}


@pytest.mark.filterwarnings("ignore:Some donated buffers")
def test_clean_serve_drain_zero_violations(tmp_path):
    """The acceptance fixture: a real Server lifecycle (warmup, mixed
    submits from several threads, drain) under the armed sanitizer
    journals ZERO lock_order_violation events — the serving plane's lock
    discipline, runtime-checked."""
    import jax.numpy as jnp

    from deep_vision_tpu.serve import Engine, Server

    jp = tmp_path / "serve.jsonl"
    journal = RunJournal(str(jp), kind="serve")
    journal.manifest(config={"name": "concurlint_serve", "task": "serving"})
    san = locksmith.arm(journal=journal, registry=Registry())

    img = (4, 4, 1)
    w = np.random.RandomState(0).randn(16, 3).astype(np.float32)
    eng = Engine(registry=Registry())
    eng.register("toy", _toy_fn, {"w": jnp.asarray(w)}, input_shape=img,
                 buckets=(1, 2, 4))
    eng.warmup()
    server = Server(eng, journal=journal, registry=Registry(),
                    max_wait_ms=2.0)
    server.start()

    errs = []

    def client(n, seed):
        rng = np.random.RandomState(seed)
        try:
            futs = [server.submit("toy", rng.rand(*img).astype(np.float32))
                    for _ in range(n)]
            for fu in futs:
                fu.result(timeout=60)
        except Exception as e:  # pragma: no cover - diagnostic
            errs.append(e)

    threads = [threading.Thread(target=client, args=(4, i))
               for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    summary = server.drain("close")
    assert not errs and summary["outcome"] == "flushed"
    assert san.violations() == []
    locksmith.disarm()
    journal.close()
    events = read_journal(str(jp))
    assert not any(e["event"] == "lock_order_violation" for e in events)
    from tools.check_journal import check_journal

    assert check_journal(str(jp), strict=True) == []
