"""The DV rule set: each rule is `check(ctx) -> list[Finding]`.

Codes map 1:1 onto the runtime signals the obs/ layer already exposes —
the linter catches at review time what the telemetry catches after the
TPU hours are spent (DV001 <-> dispatch-time breakdown, DV004 <->
recompile counter, DV005/DV002 <-> irreproducible runs the journal can
only record). See lint/README.md for the full catalog with fix recipes.
"""
from __future__ import annotations

import ast
import re
from typing import List, Optional

from deep_vision_tpu.lint.findings import Finding
from deep_vision_tpu.lint.jitctx import last_name, root_name

NUMPY_ROOTS = {"np", "numpy", "onp"}


def _finding(ctx, code: str, node: ast.AST, message: str,
             severity: str = "error") -> Finding:
    return Finding(
        code=code,
        message=message,
        path=ctx.relpath,
        line=getattr(node, "lineno", 0),
        col=getattr(node, "col_offset", 0) + 1,
        severity=severity,
        symbol=ctx.symbol_at(node),
    )


def _positional_params(fn) -> List[str]:
    """Positional parameter names, minus self/cls. Keyword-only params are
    excluded on purpose: in this codebase those are static config threaded
    through functools.partial (causal=..., axis_name=...), not traced
    arrays."""
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args]
    return [n for n in names if n not in ("self", "cls")]


# -- DV001 host-sync-in-jit --------------------------------------------------

_CASTS = {"float", "int", "bool"}
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype"}


def _is_static_expr(node: ast.AST) -> bool:
    """True when the expression is shape/metadata arithmetic (static under
    trace) rather than a device value: literals, `.shape`/`.ndim`/len().
    Every leaf must be static — `float(x.mean() * x.shape[0])` is still a
    per-step sync even though shape metadata appears in it."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        return node.attr in _STATIC_ATTRS
    if isinstance(node, ast.Subscript):
        # indexing metadata (x.shape[0], x.shape[i]) stays metadata
        return _is_static_expr(node.value)
    if isinstance(node, ast.Call):
        return last_name(node.func) == "len"
    if isinstance(node, ast.Name):
        return False
    children = [c for c in ast.iter_child_nodes(node)
                if isinstance(c, ast.expr)]
    return bool(children) and all(_is_static_expr(c) for c in children)


def check_dv001(ctx) -> List[Finding]:
    """Host synchronization inside a traced function."""
    out: List[Finding] = []
    for fn in ctx.jit.traced_functions():
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute):
                if f.attr == "item" and not node.args:
                    out.append(_finding(
                        ctx, "DV001", node,
                        ".item() inside a jitted function forces a "
                        "device->host sync every step; return the array and "
                        "fetch on the host"))
                elif f.attr == "block_until_ready":
                    out.append(_finding(
                        ctx, "DV001", node,
                        "block_until_ready inside a jitted function stalls "
                        "the dispatch pipeline; fence outside the jit "
                        "boundary"))
                elif f.attr == "device_get" and root_name(f) == "jax":
                    out.append(_finding(
                        ctx, "DV001", node,
                        "jax.device_get inside a jitted function "
                        "materializes on host; fetch after the step "
                        "returns"))
                elif f.attr in ("asarray", "array") and \
                        root_name(f) in NUMPY_ROOTS and node.args and \
                        not _is_static_expr(node.args[0]):
                    # constant tables built from literals are folded at
                    # trace time and legal; only a traced value breaks out
                    out.append(_finding(
                        ctx, "DV001", node,
                        f"np.{f.attr} on a traced value pulls it to host "
                        "and breaks the trace; use jnp." + f.attr))
            elif isinstance(f, ast.Name):
                if f.id == "print" and not all(
                        _is_static_expr(a) for a in node.args):
                    # print("literal") is a harmless trace-time log;
                    # only printing something traced is the hazard
                    out.append(_finding(
                        ctx, "DV001", node,
                        "print of a traced value runs at trace time (once), "
                        "not per step; use jax.debug.print"))
                elif f.id in _CASTS and node.args and \
                        not _is_static_expr(node.args[0]):
                    out.append(_finding(
                        ctx, "DV001", node,
                        f"{f.id}() on a traced value is a concretization "
                        "error or hidden sync; keep it an array (casts on "
                        ".shape/.ndim are fine)"))
    return out


# -- DV002 prng-key-reuse ----------------------------------------------------

_KEY_MAKERS = {"PRNGKey", "key", "wrap_key_data"}
_KEY_DERIVERS = {"split", "fold_in", "clone"}


def _jax_random_callee(call: ast.Call,
                       aliases: frozenset = frozenset()) -> Optional[str]:
    """'normal' for jax.random.normal(...) — also through a local alias of
    the jax.random module (`from jax import random`) — else None."""
    f = call.func
    if isinstance(f, ast.Attribute):
        if isinstance(f.value, ast.Attribute) and f.value.attr == "random" \
                and root_name(f) == "jax":
            return f.attr
        if isinstance(f.value, ast.Name) and f.value.id in aliases:
            return f.attr
    return None


def _is_key_origin(value: ast.AST, aliases: frozenset = frozenset()) -> bool:
    """Does this assigned expression mint or derive a PRNG key? Top-level
    only: `state = create_train_state(..., PRNGKey(0))` consumes a key, it
    does not produce one, so nested calls must not count."""
    if isinstance(value, ast.IfExp):
        return _is_key_origin(value.body, aliases) or \
            _is_key_origin(value.orelse, aliases)
    if isinstance(value, (ast.Tuple, ast.List)):
        return any(_is_key_origin(e, aliases) for e in value.elts)
    if isinstance(value, ast.Call):
        return _jax_random_callee(value, aliases) in (
            _KEY_MAKERS | _KEY_DERIVERS)
    return False


def _key_name(expr: ast.AST) -> Optional[str]:
    """'rng' for a bare name, 'r[6]' for a constant-indexed subscript of
    one (the split-then-index idiom — two uses of r[6] are as correlated
    as two uses of rng); None for anything else."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Subscript) and \
            isinstance(expr.value, ast.Name) and \
            isinstance(expr.slice, ast.Constant):
        return f"{expr.value.id}[{expr.slice.value!r}]"
    return None


def _key_base(name: str) -> str:
    return name.split("[", 1)[0]


def _bare_names(expr: ast.AST) -> List[str]:
    """Names passed directly (not through attribute access, and not inside
    nested calls — those get their own consumption event). Constant-indexed
    subscripts count under their 'r[6]' spelling."""
    out: List[str] = []

    def rec(n):
        if isinstance(n, ast.Subscript):
            kn = _key_name(n)
            if kn is not None:
                out.append(kn)
            return
        if isinstance(n, (ast.Call, ast.Attribute, ast.Lambda)):
            return
        if isinstance(n, ast.Name):
            out.append(n.id)
            return
        for child in ast.iter_child_nodes(n):
            rec(child)

    rec(expr)
    return out


def _assigned_names(node) -> List[str]:
    targets = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        targets = [node.target]
    elif isinstance(node, (ast.For, ast.AsyncFor)):
        targets = [node.target]
    names: List[str] = []
    for t in targets:
        for sub in ast.walk(t):
            if isinstance(sub, ast.Name):
                names.append(sub.id)
    return names


def check_dv002(ctx) -> List[Finding]:
    """The same PRNG key consumed twice without a split/fold_in between."""
    out: List[Finding] = []
    for scope in ctx.top_level_functions():
        out.extend(_dv002_scope(ctx, scope))
    return out


def _dv002_scope(ctx, scope) -> List[Finding]:
    aliases = frozenset(getattr(ctx, "jax_random_aliases", ()))
    parents = {}
    for parent in ast.walk(scope):
        for child in ast.iter_child_nodes(parent):
            parents[id(child)] = parent

    def loops_of(node) -> frozenset:
        loops, cur = [], node
        while id(cur) in parents:
            cur = parents[id(cur)]
            if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
                loops.append(id(cur))
        return frozenset(loops)

    def arms_of(node) -> frozenset:
        """(if-node, arm) pairs enclosing this node: two consumes in
        opposite arms of the same if never both execute, so they are one
        use each, not a reuse. Code after an if whose taken arm always
        returns/raises belongs to the other arm in effect."""
        arms, cur = [], node
        while id(cur) in parents:
            parent = parents[id(cur)]
            if isinstance(parent, (ast.If, ast.IfExp)):
                body = parent.body if isinstance(parent.body, list) \
                    else [parent.body]
                orelse = parent.orelse if isinstance(parent.orelse, list) \
                    else [parent.orelse]
                if cur in body:
                    arms.append((id(parent), "body"))
                elif cur in orelse:
                    arms.append((id(parent), "orelse"))
            def after_if(prev):
                # recurse through elif chains: code after `if: return /
                # elif: return` is exclusive with every terminal arm
                if _terminal(prev.body):
                    arms.append((id(prev), "orelse"))
                    for s in prev.orelse:
                        if isinstance(s, ast.If):
                            after_if(s)
                elif _terminal(prev.orelse):
                    arms.append((id(prev), "body"))
                    for s in prev.body:
                        if isinstance(s, ast.If):
                            after_if(s)

            for field in ("body", "orelse", "finalbody"):
                block = getattr(parent, field, None)
                if isinstance(block, list) and cur in block:
                    for prev in block[:block.index(cur)]:
                        if isinstance(prev, ast.If):
                            after_if(prev)
            cur = parent
        return frozenset(arms)

    events = []  # (line, col, kind, name, node)
    for node in ast.walk(scope):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                             ast.For, ast.AsyncFor)):
            value = getattr(node, "value", None) or getattr(node, "iter", None)
            origin = value is not None and _is_key_origin(value, aliases)
            # the binding takes effect AFTER the RHS runs: sort the assign
            # event past the value's end so `key = fold_in(key, i)` charges
            # the RHS consume to the OLD binding, not the fresh one
            if value is not None and getattr(value, "end_lineno", None):
                pos = (value.end_lineno, (value.end_col_offset or 0) + 1)
            else:
                pos = (node.lineno, node.col_offset)
            for name in _assigned_names(node):
                events.append((pos[0], pos[1],
                               "key_assign" if origin else "assign",
                               name, node))
        elif isinstance(node, ast.Call):
            jr = _jax_random_callee(node, aliases)
            if jr is not None and jr not in _KEY_MAKERS:
                # sampler or split/fold_in: consumes its first argument.
                # Derivers are tagged: `fold_in(key, i)` inside a loop is
                # the per-iteration idiom, not a reuse.
                kn = _key_name(node.args[0]) if node.args else None
                if kn is not None:
                    kind = "derive" if jr in _KEY_DERIVERS else "consume"
                    events.append((node.lineno, node.col_offset, kind,
                                   kn, node))
            elif jr is None:
                # generic call: a tracked key passed through (model apply,
                # helper fn, rngs={...}) is consumed by the callee. One use
                # per call even if the key appears twice in its arguments.
                argexprs = list(node.args) + [kw.value for kw in node.keywords]
                seen = set()
                for expr in argexprs:
                    for name in _bare_names(expr):
                        if name not in seen:
                            seen.add(name)
                            events.append((node.lineno, node.col_offset,
                                           "use", name, node))
    events.sort(key=lambda e: (e[0], e[1]))

    out: List[Finding] = []
    tracked = {}  # name -> {'paths': [arm-sets], 'assign_loops': frozenset}
    consumed_keys = {}  # names seen as jax.random sampler args (implicit)
    derives = {}  # name -> [(fingerprint, arm-set)] of split/fold_in calls

    def invalidate(name):
        # rebinding `r` also retires every tracked `r[i]` subkey
        for store in (tracked, consumed_keys, derives):
            store.pop(name, None)
            for k in [k for k in store if _key_base(k) == name]:
                del store[k]

    def implicit(name):
        # a subscripted use inherits its split's loop context: `r[6]`
        # consumed in a loop that `r = split(...)` sits outside is a reuse
        base = tracked.get(_key_base(name))
        loops = base["assign_loops"] if base else loops_of(scope)
        return consumed_keys.setdefault(
            name, {"paths": [], "assign_loops": loops})

    for line, col, kind, name, node in events:
        if kind == "key_assign":
            assign_loops = loops_of(node)
            if isinstance(node, (ast.For, ast.AsyncFor)):
                # `for k in jax.random.split(...)` binds a fresh key per
                # iteration: the For is the key's own loop, not a reuse site
                assign_loops |= {id(node)}
            invalidate(name)
            tracked[name] = {"paths": [], "assign_loops": assign_loops}
        elif kind == "assign":
            invalidate(name)
        elif kind == "derive":
            # split/fold_in are the sanctioned reuse forms: deriving the
            # same key twice is only a bug when the data arguments are
            # identical (split(key) twice yields identical subkeys) —
            # fold_in(key, 0) / fold_in(key, 1) is the canonical per-index
            # idiom and must not flag. No loop check either: fold_in(key, i)
            # inside the loop is the per-iteration fix.
            fp = _derive_fingerprint(node)
            prior = derives.setdefault(name, [])
            use_arms = arms_of(node)
            if any(f == fp and not _arms_exclusive(a, use_arms)
                   for f, a in prior):
                out.append(_finding(
                    ctx, "DV002", node,
                    f"PRNG key '{name}' is derived again with identical "
                    "arguments: split/fold_in of the same key with the "
                    "same inputs yields identical keys"))
            prior.append((fp, use_arms))
        elif kind == "consume":
            # the textbook bug: sampling from a key AFTER splitting it —
            # the parent's stream is correlated with its subkeys, so the
            # parent must be discarded (or rebound: `key, sub = split(key)`)
            use_arms = arms_of(node)
            if any(not _arms_exclusive(a, use_arms)
                   for _, a in derives.get(name, [])):
                out.append(_finding(
                    ctx, "DV002", node,
                    f"PRNG key '{name}' is consumed after being "
                    "split/folded; the parent key is correlated with its "
                    "subkeys — use a derived key instead"))
            # parameter or untracked name used directly as a key: start
            # implicit tracking so a second sampler use flags
            rec = tracked.get(name) or implicit(name)
            _dv002_use(ctx, out, rec, name, node, loops_of(node),
                       use_arms)
        elif kind == "use":
            rec = tracked.get(name) or consumed_keys.get(name)
            if rec is None and "[" in name and _key_base(name) in tracked:
                # r[6] passed to a generic call with `r` a tracked split:
                # each subkey gets one use, a second one is the gan.py bug
                rec = implicit(name)
            if rec is not None:
                _dv002_use(ctx, out, rec, name, node, loops_of(node),
                           arms_of(node))
    return out


def _terminal(stmts) -> bool:
    """Does this statement block always leave the enclosing scope/block?"""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


def _arms_exclusive(a: frozenset, b: frozenset) -> bool:
    """Two events sit in opposite arms of the same if: at most one runs."""
    flip = {"body": "orelse", "orelse": "body"}
    return any((if_id, flip[arm]) in b for if_id, arm in a)


def _derive_fingerprint(call: ast.Call) -> tuple:
    """Identity of a split/fold_in call minus its key argument: two derives
    of one key collide only when every data argument is identical. A bare
    split(key) is normalized to its num=2 default so split(key) and
    split(key, 2) collide."""
    f = call.func
    fn = f.attr if isinstance(f, ast.Attribute) else getattr(f, "id", "")
    data = tuple(ast.dump(a) for a in call.args[1:])
    kws = tuple(sorted((kw.arg or "", ast.dump(kw.value))
                       for kw in call.keywords))
    if fn == "split" and not data and not kws:
        data = (ast.dump(ast.Constant(2)),)
    return (fn, data, kws)


def _dv002_use(ctx, out, rec, name, node, use_loops, use_arms) -> None:
    fresh_loops = use_loops - rec["assign_loops"]
    # a prior consume on a branch path that can co-execute with this one
    # is a reuse; consumes in mutually exclusive if/else arms are not
    reuse = any(not _arms_exclusive(prev, use_arms)
                for prev in rec["paths"])
    rec["paths"].append(use_arms)
    if reuse:
        out.append(_finding(
            ctx, "DV002", node,
            f"PRNG key '{name}' is consumed again without an intervening "
            "jax.random.split/fold_in: correlated randomness"))
    elif fresh_loops:
        out.append(_finding(
            ctx, "DV002", node,
            f"PRNG key '{name}' is consumed inside a loop but derived "
            "outside it: every iteration sees the same randomness; "
            "fold_in the iteration index"))


# -- DV003 missing-donation --------------------------------------------------

_DV003_TARGET = re.compile(r"step|update|train", re.I)
_DV003_EXCLUDE = re.compile(
    r"eval|infer|predict|sample|generate|forward|fwd|decode|loss|apply", re.I)
_STATEFUL_PARAM = re.compile(r"(^|_)(state|params)$|^(opt|g|d)_?state")


def check_dv003(ctx) -> List[Finding]:
    """Jitted train/update steps that never donate their state buffers."""
    out: List[Finding] = []
    for site in ctx.jit.sites:
        if site.donated:
            continue
        name = site.target_name or ""
        if not _DV003_TARGET.search(name) or _DV003_EXCLUDE.search(name):
            continue
        if site.target is not None and not isinstance(site.target,
                                                      ast.Lambda):
            params = _positional_params(site.target)
            if not any(_STATEFUL_PARAM.search(p) for p in params):
                continue
        out.append(_finding(
            ctx, "DV003", site.node,
            f"jitted step '{name}' takes a params/opt-state pytree but "
            "declares no donate_argnums/donate_argnames: the old state "
            "stays resident and doubles peak HBM"))
    return out


# -- DV004 jit-in-loop -------------------------------------------------------

# the one sanctioned compile loop: serving warms its (model, bucket)
# executables inside functions named like warmup (serve/engine.py); the
# same AOT chain anywhere else in a loop — above all a request/dispatch
# loop — is compilation at serve time. Anchored to the name's start so
# merely containing 'warm' (swarm_dispatch) does not punch a hole in
# the gate.
_DV004_WARMUP = re.compile(r"^(_*)((re|pre)?warm|preload|aot_|startup)",
                           re.I)


def _is_aot_compile_chain(call: ast.Call) -> bool:
    """`<expr>.lower(...).compile(...)` — the AOT warmup chain. Bare
    `.compile()` on a non-lower receiver (re.compile, a compiled
    executable cached outside the loop) is not it."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "compile"):
        return False
    recv = f.value
    return isinstance(recv, ast.Call) and \
        isinstance(recv.func, ast.Attribute) and recv.func.attr == "lower"


def check_dv004(ctx) -> List[Finding]:
    """jax.jit constructed (or re-applied) inside a loop body; serve-aware:
    also AOT .lower().compile() in any loop outside a warmup function."""
    out: List[Finding] = []

    def _is_jax_jit(func: ast.AST) -> bool:
        # bare `jit(...)` is almost certainly `from jax import jit`;
        # an attribute call must root at jax (or the pjit module) so a
        # non-JAX `.jit()` method (self.jit, compiler.jit) isn't flagged
        if isinstance(func, ast.Name):
            return func.id in ("jit", "pjit")
        if isinstance(func, ast.Attribute):
            return func.attr in ("jit", "pjit") and \
                root_name(func) in ("jax", "pjit")
        return False

    def scan(node, in_loop: bool, fname: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                # the def's body runs later, but its decorators run now
                if in_loop:
                    for dec in child.decorator_list:
                        dt = dec if not isinstance(dec, ast.Call) \
                            else dec.func
                        if _is_jax_jit(dt):
                            out.append(_finding(
                                ctx, "DV004", dec,
                                "a jit-decorated function defined inside a "
                                "loop builds a fresh jit (and cache) every "
                                "iteration; hoist the definition"))
                # body executes when called, not per-iter; track the new
                # enclosing-function name for the warmup exemption
                scan(child, False,
                     child.name if not isinstance(child, ast.ClassDef)
                     else fname)
                continue
            if isinstance(child, ast.Lambda):
                continue
            if isinstance(child, ast.Call) and in_loop and \
                    _is_jax_jit(child.func) and \
                    (child.args or child.keywords) and \
                    not _DV004_WARMUP.search(fname):
                # warmup functions are exempt from both forms: compiling
                # per loop iteration is the POINT of a warmup pass (one
                # jit per model, one lower/compile per bucket)
                out.append(_finding(
                    ctx, "DV004", child,
                    "jax.jit(...) inside a loop creates a new compiled "
                    "function (and recompile) every iteration; hoist it "
                    "out of the loop"))
            elif isinstance(child, ast.Call) and in_loop and \
                    _is_aot_compile_chain(child) and \
                    not _DV004_WARMUP.search(fname):
                out.append(_finding(
                    ctx, "DV004", child,
                    ".lower(...).compile(...) inside a loop compiles at "
                    "serve/run time; bucket executables must be built "
                    "once in a warmup path (a function named warm*), "
                    "never in a request/dispatch loop"))
            scan(child, in_loop or isinstance(
                child, (ast.For, ast.While, ast.AsyncFor)), fname)

    scan(ctx.tree, False, "")
    return out


# -- DV005 impure-jit --------------------------------------------------------

_IMPURE_TIME = {"time", "perf_counter", "monotonic", "process_time",
                "time_ns", "perf_counter_ns"}


def check_dv005(ctx) -> List[Finding]:
    """Side effects inside a traced function: silently frozen at trace time."""
    out: List[Finding] = []
    for fn in ctx.jit.traced_functions():
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id in ("self", "cls"):
                        out.append(_finding(
                            ctx, "DV005", node,
                            f"assignment to {t.value.id}.{t.attr} inside a "
                            "jitted function runs once at trace time, not "
                            "per step; return the value instead"))
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                out.append(_finding(
                    ctx, "DV005", node,
                    f"{'global' if isinstance(node, ast.Global) else 'nonlocal'}"
                    " write inside a jitted function is a trace-time side "
                    "effect; thread the value through the return"))
            elif isinstance(node, ast.Call):
                f = node.func
                if not isinstance(f, ast.Attribute):
                    continue
                root = root_name(f)
                if root == "time" and f.attr in _IMPURE_TIME:
                    out.append(_finding(
                        ctx, "DV005", node,
                        f"time.{f.attr}() inside a jitted function is "
                        "evaluated once at trace time; time on the host "
                        "around the step"))
                elif root in NUMPY_ROOTS and isinstance(f.value,
                                                        ast.Attribute) \
                        and f.value.attr == "random":
                    out.append(_finding(
                        ctx, "DV005", node,
                        f"np.random.{f.attr} inside a jitted function "
                        "freezes one host sample into the trace; use "
                        "jax.random with an explicit key"))
                elif root == "random" and isinstance(f.value, ast.Name) \
                        and f.value.id not in getattr(
                            ctx, "jax_random_aliases", ()):
                    out.append(_finding(
                        ctx, "DV005", node,
                        f"random.{f.attr} inside a jitted function freezes "
                        "one host sample into the trace; use jax.random"))
    return out


# -- DV007 trace-time-constant ----------------------------------------------

# the alias forms DV005's attribute matching cannot see: DV005 catches
# `time.time()` / `np.random.rand()` / `random.random()` spelled as
# attribute calls; DV007 closes the holes — bare names imported with
# `from time import time` / `from random import ...` /
# `from numpy.random import ...`, and method calls on a host RNG object
# (`rng = np.random.default_rng(...)`; `rng.normal()` inside jit).

_RNG_FACTORIES = {"default_rng", "RandomState", "Generator"}


def _dv007_aliases(tree: ast.Module, jax_aliases: frozenset) -> tuple:
    """-> (bare-call aliases, datetime-class aliases). The first maps a
    bare name to its impure dotted form (calling the NAME is the trap:
    `time()`, `randint()`); the second holds local names for the
    datetime/date classes, where only `.now()`/`.today()` is impure —
    the constructor itself (`datetime(1970, 1, 1)`) is a pure literal
    and must not flag."""
    out = {}
    dt_classes = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == "time":
                for a in node.names:
                    if a.name in _IMPURE_TIME:
                        out[a.asname or a.name] = f"time.{a.name}"
            elif node.module == "random":
                for a in node.names:
                    out[a.asname or a.name] = f"random.{a.name}"
            elif node.module in ("numpy.random", "onp.random"):
                for a in node.names:
                    out[a.asname or a.name] = f"np.random.{a.name}"
            elif node.module == "datetime":
                for a in node.names:
                    if a.name in ("datetime", "date"):
                        dt_classes.add(a.asname or a.name)
    # names bound to jax.random are samplers with explicit keys, not traps
    for name in jax_aliases:
        out.pop(name, None)
    return out, dt_classes


def _dv007_rng_objects(tree: ast.Module) -> set:
    """Names assigned a host RNG object (module- or function-level):
    `rng = np.random.default_rng(0)` / `RandomState(7)`."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if last_name(node.value.func) in _RNG_FACTORIES:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def check_dv007(ctx) -> List[Finding]:
    """Host time/RNG reached through aliases or generator objects inside a
    traced function: evaluated once, frozen into the trace as a constant."""
    aliases, dt_classes = _dv007_aliases(
        ctx.tree, frozenset(getattr(ctx, "jax_random_aliases", ())))
    rng_objects = _dv007_rng_objects(ctx.tree)
    out: List[Finding] = []
    for fn in ctx.jit.traced_functions():
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id in aliases:
                out.append(_finding(
                    ctx, "DV007", node,
                    f"{f.id}() (= {aliases[f.id]}) inside a jitted "
                    "function is evaluated once at trace time and frozen "
                    "into the graph as a constant; time on the host around "
                    "the step / use jax.random with an explicit key"))
            elif isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id in rng_objects:
                out.append(_finding(
                    ctx, "DV007", node,
                    f"host RNG call {f.value.id}.{f.attr}() inside a "
                    "jitted function freezes one sample into the trace; "
                    "use jax.random with an explicit key"))
            elif isinstance(f, ast.Attribute) and f.attr in ("now", "today") \
                    and root_name(f) in ({"datetime", "date"} | dt_classes):
                out.append(_finding(
                    ctx, "DV007", node,
                    f"{ast.unparse(f) if hasattr(ast, 'unparse') else f.attr}"
                    "() inside a jitted function is a trace-time constant; "
                    "take timestamps on the host"))
    return out


# -- DV006 untraced-python-branch -------------------------------------------

def _naked_param_refs(test: ast.AST, params) -> List[str]:
    refs: List[str] = []

    def rec(n):
        if isinstance(n, ast.Attribute):
            return  # x.shape, state.batch_stats: static structure
        if isinstance(n, ast.Call):
            return  # isinstance/len/... treated as static predicates
        if isinstance(n, ast.Compare) and any(
                isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                for op in n.ops):
            return  # `x is None` / `"k" in d`: argument-structure checks
        if isinstance(n, ast.Name):
            if n.id in params:
                refs.append(n.id)
            return
        if isinstance(n, ast.Subscript):
            if isinstance(n.value, ast.Name) and n.value.id in params:
                refs.append(n.value.id)
                return
            rec(n.value)
            return
        for child in ast.iter_child_nodes(n):
            rec(child)

    rec(test)
    return refs


def check_dv006(ctx) -> List[Finding]:
    """Python `if`/`while` on a traced argument (heuristic, warn-level)."""
    out: List[Finding] = []
    for fn in ctx.jit.traced_functions():
        if isinstance(fn, ast.Lambda):
            continue
        # closures over the jitted function's arguments are traced too, so
        # nested defs are checked against the union of positional params
        params = set(_positional_params(fn))
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                params |= set(_positional_params(node))
        if not params:
            continue
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                refs = _naked_param_refs(node.test, params)
                if refs:
                    kw = "while" if isinstance(node, ast.While) else "if"
                    out.append(_finding(
                        ctx, "DV006", node,
                        f"Python `{kw}` on traced argument "
                        f"'{refs[0]}' fails or retraces under jit; use "
                        "jax.lax.cond/select (static config branches: "
                        "suppress with a reason)",
                        severity="warning"))
    return out


# -- registry ----------------------------------------------------------------

RULES = {
    "DV001": ("host-sync-in-jit", "error", check_dv001,
              "device->host synchronization inside a traced function"),
    "DV002": ("prng-key-reuse", "error", check_dv002,
              "a PRNG key consumed twice without split/fold_in"),
    "DV003": ("missing-donation", "error", check_dv003,
              "jitted train/update step without donate_argnums"),
    "DV004": ("jit-in-loop", "error", check_dv004,
              "jax.jit or AOT lower().compile() inside a loop body"),
    "DV005": ("impure-jit", "error", check_dv005,
              "host side effects inside a traced function"),
    "DV006": ("untraced-python-branch", "warning", check_dv006,
              "Python control flow on a traced argument"),
    "DV007": ("trace-time-constant", "error", check_dv007,
              "host time/RNG via import aliases or RNG objects in a "
              "traced function"),
}

# the DV1xx concurrency pack (lint/concur.py) rides the same engine:
# one RULES registry, one baseline, one suppression syntax, one CLI.
# concur.py imports only findings/jitctx, so this merge is cycle-free.
from deep_vision_tpu.lint.concur import CONCUR_RULES  # noqa: E402

RULES.update(CONCUR_RULES)

# the DV2xx distributed-correctness pack (lint/distlint.py): platform
# registry, bounded collectives, env-knob registry, journal schemas,
# sharding-table hygiene. Same cycle-free import shape as concur.
from deep_vision_tpu.lint.distlint import DIST_RULES  # noqa: E402

RULES.update(DIST_RULES)
