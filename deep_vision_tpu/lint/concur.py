"""concurlint: the DV1xx concurrency rule pack (thread-safety analysis).

jaxlint's DV001-DV007 gate the JAX/TPU contracts; this pack gates the
repo's SECOND failure domain — the threading that grew around the
serving/observability stack (serve router + queue dispatchers, flight
recorder taps, health watchdog, data workers, preemption handlers).
The codes encode, mechanically, the exact bug classes the PR 5/6 review
logs caught by hand:

  DV101 shared-mutable-state   an attribute written both from a thread
        target (threading.Thread / executor.submit) and from another
        method without a common `with self._lock:` guard — per-class
        lock-domain inference over `ast`.
  DV102 lock-order inversion   the static lock-order graph built from
        nested `with lockA: with lockB:` scopes (including across call
        edges between functions/methods of the module) contains a
        cycle — the lockdep check, at review time.
  DV103 signal-unsafe handler  a blocking call (lock acquire, journal
        write, Future.result, queue put/get, thread join, flight dump)
        reachable from a `signal.signal` handler — the PR 5 bug: a
        SIGTERM handler dumping a flight bundle can self-deadlock on
        the journal/recorder locks the interrupted thread holds.
  DV104 future-protocol misuse set_result/set_exception on a Future the
        scope did not create, without set_running_or_notify_cancel —
        the PR 6 bug: a client-cancelled Future raises
        InvalidStateError and fails the rest of its batch.

Analysis is per-module and name-based, like the rest of jaxlint: lock
identity is `Class.attr` (or a module-global name), call edges are
followed for `self.method()` and bare module-function calls only.
Cross-module lock orders (journal lock vs flight lock vs device lock at
runtime) are the *dynamic* residue this pack deliberately leaves to
obs/locksmith.py, the runtime sanitizer armed in serve-smoke and
chaos-smoke. See lint/README.md for the catalog with fix recipes.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from deep_vision_tpu.lint.findings import Finding
from deep_vision_tpu.lint.jitctx import last_name, root_name

# factories whose result is a mutual-exclusion object: stdlib threading
# plus the obs/locksmith instrumented wrappers the repo adopts
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore", "lock", "rlock", "condition"}
_REENTRANT_FACTORIES = {"RLock", "rlock"}
_QUEUE_FACTORIES = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}


def _finding(ctx, code: str, node: ast.AST, message: str,
             severity: str = "error") -> Finding:
    return Finding(
        code=code,
        message=message,
        path=ctx.relpath,
        line=getattr(node, "lineno", 0),
        col=getattr(node, "col_offset", 0) + 1,
        severity=severity,
        symbol=ctx.symbol_at(node),
    )


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for `self.x` / `cls.x`, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and \
            node.value.id in ("self", "cls"):
        return node.attr
    return None


def _is_lock_factory(value: ast.AST) -> Optional[str]:
    """Factory name when `value` constructs a lock-like object
    (threading.Lock(), locksmith.lock("...")), else None."""
    if isinstance(value, ast.Call):
        name = last_name(value.func)
        if name in _LOCK_FACTORIES:
            return name
    return None


class _ClassInfo:
    """Per-class lock domain: methods, lock attrs, thread entry points."""

    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.methods: Dict[str, ast.AST] = {}
        for child in node.body:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[child.name] = child
        # attrs assigned a lock factory anywhere in the class, + their
        # reentrancy (RLock nests legally, Lock does not)
        self.lock_attrs: Dict[str, bool] = {}
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                fac = _is_lock_factory(sub.value)
                if fac is None:
                    continue
                for t in sub.targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        self.lock_attrs[attr] = fac in _REENTRANT_FACTORIES
        # attrs assigned queue.Queue()-likes (for DV103's queue-op check)
        self.queue_attrs: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and \
                    isinstance(sub.value, ast.Call) and \
                    last_name(sub.value.func) in _QUEUE_FACTORIES:
                for t in sub.targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        self.queue_attrs.add(attr)
        self.thread_entries = self._thread_entries()

    def _thread_entries(self) -> Set[str]:
        """Method names handed to `threading.Thread(target=self.m)` or
        `executor.submit(self.m, ...)` anywhere in the class — the roots
        of the concurrent lock domain. Only defined methods count: an
        attribute like `self.transform` (a user callback) is not ours to
        analyze."""
        out: Set[str] = set()
        for sub in ast.walk(self.node):
            if not isinstance(sub, ast.Call):
                continue
            fname = last_name(sub.func)
            if fname == "Thread":
                for kw in sub.keywords:
                    if kw.arg == "target":
                        attr = _self_attr(kw.value)
                        if attr in self.methods:
                            out.add(attr)
            elif fname == "submit" and isinstance(sub.func, ast.Attribute) \
                    and sub.args:
                attr = _self_attr(sub.args[0])
                if attr in self.methods:
                    out.add(attr)
        return out

    def reachable(self, roots: Set[str]) -> Set[str]:
        """Closure of `roots` over same-class `self.m()` calls."""
        seen = set(roots)
        frontier = list(roots)
        while frontier:
            m = frontier.pop()
            fn = self.methods.get(m)
            if fn is None:
                continue
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    callee = _self_attr(sub.func)
                    if callee in self.methods and callee not in seen:
                        seen.add(callee)
                        frontier.append(callee)
        return seen


def _module_locks(tree: ast.Module) -> Dict[str, bool]:
    """Module-global `NAME = threading.Lock()` style locks -> reentrant?"""
    out: Dict[str, bool] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign):
            fac = _is_lock_factory(node.value)
            if fac is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = fac in _REENTRANT_FACTORIES
    return out


def _classes(tree: ast.Module) -> List[_ClassInfo]:
    return [_ClassInfo(n) for n in ast.walk(tree)
            if isinstance(n, ast.ClassDef)]


def _guards_of(node: ast.AST, parents: Dict[int, ast.AST],
               fn: ast.AST) -> Set[str]:
    """Lock guards held at `node` within `fn`: the attr/name of every
    enclosing `with self.X:` / `with NAME:` item. Generous on purpose —
    any with-context over a bare self-attr or name counts as a guard, so
    an unrecognized lock factory never produces a false positive."""
    guards: Set[str] = set()
    cur = node
    while id(cur) in parents and cur is not fn:
        parent = parents[id(cur)]
        if isinstance(parent, (ast.With, ast.AsyncWith)) and \
                cur in parent.body:
            for item in parent.items:
                attr = _self_attr(item.context_expr)
                if attr is not None:
                    guards.add("self." + attr)
                elif isinstance(item.context_expr, ast.Name):
                    guards.add(item.context_expr.id)
        cur = parent
    return guards


def _parent_map(root: ast.AST) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    for parent in ast.walk(root):
        for child in ast.iter_child_nodes(parent):
            parents[id(child)] = parent
    return parents


# -- DV101 shared-mutable-state ----------------------------------------------

def check_dv101(ctx) -> List[Finding]:
    """A self-attribute written both from a thread target and from another
    method without a common lock guard."""
    out: List[Finding] = []
    for cls in _classes(ctx.tree):
        if not cls.thread_entries:
            continue
        thread_methods = cls.reachable(cls.thread_entries)
        # attr -> list of (method, guards, node, in_thread_domain)
        writes: Dict[str, List[Tuple[str, Set[str], ast.AST, bool]]] = {}
        for mname, fn in cls.methods.items():
            if mname in ("__init__", "__new__"):
                continue  # construction happens-before every thread start
            parents = _parent_map(fn)
            for sub in ast.walk(fn):
                if isinstance(sub, (ast.Assign, ast.AugAssign,
                                    ast.AnnAssign)):
                    targets = (sub.targets if isinstance(sub, ast.Assign)
                               else [sub.target])
                    for t in targets:
                        attr = _self_attr(t)
                        if attr is None or attr in cls.lock_attrs:
                            continue
                        writes.setdefault(attr, []).append(
                            (mname, _guards_of(sub, parents, fn), sub,
                             mname in thread_methods))
        for attr, events in sorted(writes.items()):
            threaded = [e for e in events if e[3]]
            external = [e for e in events if not e[3]]
            if not threaded or not external:
                continue
            for t_m, t_g, t_node, _ in threaded:
                for x_m, x_g, x_node, _ in external:
                    if t_g & x_g:
                        continue
                    # flag the unguarded side (the usual fix site); when
                    # both hold disjoint locks, flag the thread-side write
                    node = (x_node if not x_g and t_g else t_node)
                    out.append(_finding(
                        ctx, "DV101", node,
                        f"attribute 'self.{attr}' is written from thread "
                        f"target '{t_m}' and from '{x_m}' without a common "
                        "lock guard: a torn/raced write under free-running "
                        "threads; guard both writes with one `with "
                        "self._lock:`"))
                    break  # one finding per (threaded write, attr)
                else:
                    continue
                break  # one finding per attr keeps the report readable
    return out


# -- DV102 lock-order inversion ----------------------------------------------

class _FnLocks:
    """Per-function lock behavior: direct nesting edges, the set of locks
    it may acquire, and the calls it makes while holding locks."""

    def __init__(self):
        self.edges: List[Tuple[str, str, ast.AST]] = []
        self.acquires: Set[str] = set()
        self.acquire_nodes: Dict[str, ast.AST] = {}
        # (held-locks frozenset, callee key, call node)
        self.calls: List[Tuple[frozenset, str, ast.AST]] = []


def _lock_key(expr: ast.AST, cls: Optional[_ClassInfo],
              module_locks: Dict[str, bool]) -> Optional[Tuple[str, bool]]:
    """(graph key, reentrant) for a with-item that is a known lock."""
    attr = _self_attr(expr)
    if attr is not None and cls is not None and attr in cls.lock_attrs:
        return f"{cls.node.name}.{attr}", cls.lock_attrs[attr]
    if isinstance(expr, ast.Name) and expr.id in module_locks:
        return expr.id, module_locks[expr.id]
    return None


def _scan_fn_locks(fn: ast.AST, cls: Optional[_ClassInfo],
                   module_locks: Dict[str, bool],
                   module_fns: Set[str]) -> _FnLocks:
    info = _FnLocks()

    def rec(node: ast.AST, held: List[Tuple[str, bool]]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue  # nested defs run on their own call stack
            if isinstance(child, (ast.With, ast.AsyncWith)):
                acquired: List[Tuple[str, bool]] = []
                for item in child.items:
                    key = _lock_key(item.context_expr, cls, module_locks)
                    if key is None:
                        continue
                    name, reentrant = key
                    info.acquires.add(name)
                    info.acquire_nodes.setdefault(name, item.context_expr)
                    held_names = [h for h, _ in held + acquired]
                    if name in held_names and not reentrant:
                        # self-cycle: nested acquisition of one
                        # non-reentrant lock deadlocks unconditionally
                        info.edges.append((name, name, item.context_expr))
                    else:
                        for h in held_names:
                            if h != name:
                                info.edges.append((h, name,
                                                   item.context_expr))
                    acquired.append((name, reentrant))
                rec(child, held + acquired)
                continue
            if isinstance(child, ast.Call) and held:
                callee = None
                attr = _self_attr(child.func)
                if attr is not None and cls is not None and \
                        attr in cls.methods:
                    callee = f"{cls.node.name}.{attr}"
                elif isinstance(child.func, ast.Name) and \
                        child.func.id in module_fns:
                    callee = child.func.id
                if callee is not None:
                    info.calls.append((
                        frozenset(h for h, _ in held), callee, child))
            rec(child, held)

    rec(fn, [])
    return info


def check_dv102(ctx) -> List[Finding]:
    """Cycle in the module's static lock-order graph (nested with-scopes,
    propagated across intra-module call edges)."""
    module_locks = _module_locks(ctx.tree)
    classes = _classes(ctx.tree)
    has_class_locks = any(c.lock_attrs for c in classes)
    if not module_locks and not has_class_locks:
        return []
    module_fns = {n.name for n in ctx.tree.body
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

    fn_infos: Dict[str, _FnLocks] = {}
    for cls in classes:
        for mname, fn in cls.methods.items():
            fn_infos[f"{cls.node.name}.{mname}"] = _scan_fn_locks(
                fn, cls, module_locks, module_fns)
    for node in ctx.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_infos[node.name] = _scan_fn_locks(
                node, None, module_locks, module_fns)

    # transitive acquire sets (fixed point over the intra-module call graph)
    changed = True
    while changed:
        changed = False
        for info in fn_infos.values():
            for _, callee, _ in info.calls:
                target = fn_infos.get(callee)
                if target and not target.acquires <= info.acquires:
                    info.acquires |= target.acquires
                    changed = True

    # edge set: direct nesting + (held -> everything a callee may acquire)
    edges: Dict[Tuple[str, str], ast.AST] = {}
    for info in fn_infos.values():
        for a, b, node in info.edges:
            edges.setdefault((a, b), node)
        for held, callee, node in info.calls:
            target = fn_infos.get(callee)
            if target is None:
                continue
            for h in held:
                for l in target.acquires:
                    if h != l:
                        edges.setdefault((h, l), node)
                    elif not _reentrant(h, classes, module_locks):
                        edges.setdefault((h, h), node)

    # cycles: self-loops + any edge inside a multi-node SCC
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    sccs = _tarjan(graph)
    scc_of = {n: i for i, scc in enumerate(sccs) for n in scc}
    out: List[Finding] = []
    reported: Set[Tuple[str, str]] = set()
    for (a, b), node in sorted(
            edges.items(),
            key=lambda kv: (getattr(kv[1], "lineno", 0),
                            getattr(kv[1], "col_offset", 0))):
        if a == b:
            out.append(_finding(
                ctx, "DV102", node,
                f"nested acquisition of non-reentrant lock '{a}': the "
                "inner acquire deadlocks on the outer hold; use an RLock "
                "or restructure the critical section"))
            continue
        if scc_of.get(a) == scc_of.get(b) and \
                len(sccs[scc_of[a]]) > 1 and (b, a) not in reported:
            cycle = " <-> ".join(sorted(sccs[scc_of[a]]))
            out.append(_finding(
                ctx, "DV102", node,
                f"lock-order inversion: '{a}' is held while acquiring "
                f"'{b}', but elsewhere the order reverses (cycle: {cycle}) "
                "— two threads taking opposite paths deadlock; pick one "
                "global order"))
            reported.add((a, b))
    return out


def _reentrant(name: str, classes: List[_ClassInfo],
               module_locks: Dict[str, bool]) -> bool:
    if name in module_locks:
        return module_locks[name]
    if "." in name:
        cname, attr = name.split(".", 1)
        for c in classes:
            if c.node.name == cname:
                return c.lock_attrs.get(attr, False)
    return False


def _tarjan(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Iterative Tarjan SCC (the graphs here are tiny, but recursion
    depth must not depend on lint input)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for root in graph:
        if root in index:
            continue
        work = [(root, iter(sorted(graph[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph[nxt]))))
                    advanced = True
                    break
                elif nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)
    return sccs


# -- DV103 signal-unsafe handler ---------------------------------------------

#: attribute calls that block (or may block indefinitely) and are never
#: safe from signal context, where the interrupted thread may hold the
#: very lock the call needs
_BLOCKING_ATTRS = {
    "acquire": "acquires a lock",
    "result": "blocks on a Future",
    "join": "joins a thread",
}


def check_dv103(ctx) -> List[Finding]:
    """Blocking calls reachable from a signal handler."""
    module_locks = _module_locks(ctx.tree)
    classes = _classes(ctx.tree)
    by_name = {c.node.name: c for c in classes}
    module_fns = {n.name: n for n in ctx.tree.body
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    cls_of_fn: Dict[int, _ClassInfo] = {}
    for c in classes:
        for fn in c.methods.values():
            cls_of_fn[id(fn)] = c

    # handler roots: second arg of signal.signal(...)
    handlers: List[Tuple[ast.AST, Optional[_ClassInfo]]] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and
                last_name(node.func) == "signal" and
                root_name(node.func) in ("signal", None) and
                len(node.args) >= 2):
            continue
        target = node.args[1]
        attr = _self_attr(target)
        if attr is not None:
            # self._on_sigterm: resolve within the enclosing class (the
            # registration site's class, found via the symbol table)
            sym = ctx.symbol_at(node)
            cname = sym.split(".", 1)[0] if sym else ""
            cls = by_name.get(cname)
            if cls is not None and attr in cls.methods:
                handlers.append((cls.methods[attr], cls))
        elif isinstance(target, ast.Name) and target.id in module_fns:
            handlers.append((module_fns[target.id], None))

    out: List[Finding] = []
    flagged: Set[int] = set()
    for handler, cls in handlers:
        # reachability: direct self.m() / module fn() calls, transitively.
        # References that are merely *passed* (Thread(target=...)) run on
        # another thread, outside signal context, and are NOT followed —
        # that is exactly the sanctioned PR 5 fix shape.
        seen: Set[int] = set()
        frontier: List[Tuple[ast.AST, Optional[_ClassInfo]]] = [
            (handler, cls)]
        while frontier:
            fn, fcls = frontier.pop()
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            hname = getattr(handler, "name", "<handler>")
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                reason = _dv103_blocking(node, fcls, module_locks)
                if reason and id(node) not in flagged:
                    flagged.add(id(node))
                    out.append(_finding(
                        ctx, "DV103", node,
                        f"{reason} reachable from signal handler "
                        f"'{hname}': the handler interrupts a thread that "
                        "may hold the same lock — self-deadlock; set a "
                        "flag (threading.Event) and do the work outside "
                        "signal context, or hand it to a daemon thread"))
                # follow call edges
                attr = _self_attr(node.func)
                if attr is not None and fcls is not None and \
                        attr in fcls.methods:
                    frontier.append((fcls.methods[attr], fcls))
                elif isinstance(node.func, ast.Name) and \
                        node.func.id in module_fns:
                    frontier.append((module_fns[node.func.id], None))
            # `with self._lock:` in the handler body is an acquire too
            for node in ast.walk(fn):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        key = _lock_key(item.context_expr, fcls,
                                        module_locks)
                        if key is not None and \
                                id(item.context_expr) not in flagged:
                            flagged.add(id(item.context_expr))
                            out.append(_finding(
                                ctx, "DV103", item.context_expr,
                                f"lock '{key[0]}' acquired inside code "
                                f"reachable from signal handler "
                                f"'{hname}': the interrupted thread may "
                                "hold it — self-deadlock; set a flag and "
                                "acquire outside signal context"))
    out.sort(key=lambda f: (f.line, f.col))
    return out


def _dv103_blocking(call: ast.Call, cls: Optional[_ClassInfo],
                    module_locks: Dict[str, bool]) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        if f.id == "emergency_dump":
            return "flight bundle dump (journal + recorder locks)"
        return None
    if not isinstance(f, ast.Attribute):
        return None
    if f.attr == "emergency_dump":
        return "flight bundle dump (journal + recorder locks)"
    if f.attr in _BLOCKING_ATTRS:
        # ", ".join(...) is a str method, not a thread join
        if f.attr == "join" and isinstance(f.value, ast.Constant):
            return None
        recv = _self_attr(f.value)
        if f.attr == "acquire" and recv is not None and cls is not None \
                and recv not in cls.lock_attrs and \
                recv not in cls.queue_attrs:
            return None  # .acquire on a non-lock attr of ours: unknown
        return f"blocking call .{f.attr}() ({_BLOCKING_ATTRS[f.attr]})"
    if f.attr in ("put", "get"):
        recv = _self_attr(f.value)
        if recv is not None and cls is not None and \
                recv in cls.queue_attrs:
            return f"queue .{f.attr}() (may block on the queue lock)"
        if isinstance(f.value, ast.Name) and f.value.id in ("q", "queue"):
            return f"queue .{f.attr}() (may block on the queue lock)"
        return None
    if f.attr == "write":
        chain = f.value
        tail = _self_attr(chain) or (chain.id if isinstance(chain, ast.Name)
                                     else None)
        if tail in ("journal", "_journal"):
            return "journal write (takes the journal lock)"
    return None


# -- DV104 future-protocol misuse --------------------------------------------

def check_dv104(ctx) -> List[Finding]:
    """set_result/set_exception on a non-local Future without
    set_running_or_notify_cancel."""
    out: List[Finding] = []
    for fn in ctx.top_level_functions():
        notified = any(
            isinstance(n, ast.Call) and
            last_name(n.func) == "set_running_or_notify_cancel"
            for n in ast.walk(fn))
        if notified:
            continue
        # futures created locally are promises the scope owns: nobody can
        # have cancelled them before the first set_*, so the protocol
        # call is not required
        local_futures: Set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                    and last_name(n.value.func) == "Future":
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        local_futures.add(t.id)
        for n in ast.walk(fn):
            if not (isinstance(n, ast.Call) and
                    isinstance(n.func, ast.Attribute) and
                    n.func.attr in ("set_result", "set_exception")):
                continue
            recv = n.func.value
            if isinstance(recv, ast.Name) and recv.id in local_futures:
                continue
            out.append(_finding(
                ctx, "DV104", n,
                f".{n.func.attr}() on a Future this scope did not create, "
                "without set_running_or_notify_cancel(): a client-"
                "cancelled Future raises InvalidStateError here and can "
                "fail the whole batch; gate the resolution on "
                "set_running_or_notify_cancel() and account the "
                "cancellation"))
    return out


# -- registry ----------------------------------------------------------------

CONCUR_RULES = {
    "DV101": ("shared-mutable-state", "error", check_dv101,
              "attribute written from a thread target and another method "
              "without a common lock"),
    "DV102": ("lock-order-inversion", "error", check_dv102,
              "cycle in the static lock-order graph (nested with scopes)"),
    "DV103": ("signal-unsafe-handler", "error", check_dv103,
              "blocking call reachable from a signal.signal handler"),
    "DV104": ("future-protocol-misuse", "error", check_dv104,
              "set_result/set_exception without "
              "set_running_or_notify_cancel"),
}
