"""Cold-path tier-1 suite: the persistent executable cache
(core/excache.py) + int8 serving quantization (serve/quantize.py).

Cache correctness: round-trip bit-identity, version/platform/mesh-key
invalidation (a skewed entry journals `excache_invalid` and falls
through to the compiler — never loads), corrupt-entry quarantine,
concurrent warmers over one dir (locksmith-armed), Engine warmup
integration (zero backend compiles over a warm cache), pool
fresh-engine respawn, and the Trainer's cached step dispatch. Int8:
dequant parity, the accuracy-delta gate firing on a poisoned
calibration, scale sidecar round-trip through the crc32c checkpoint,
and hot-swap of a re-quantized tree through the existing machinery.
The multi-process zero-compile proof is `make cache-smoke`
(tools/cache_smoke.py); everything here is in-process tier-1.
"""
import json
import os
import pickle
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.core.excache import (
    EXCACHE_INVALID_REASONS,
    ExecutableCache,
    env_fingerprint,
)
from deep_vision_tpu.obs import RunJournal, locksmith, read_journal
from deep_vision_tpu.obs.registry import Registry
from deep_vision_tpu.obs.stepclock import recompile_count
from deep_vision_tpu.serve import Engine
from deep_vision_tpu.serve.quantize import (
    QuantizationRejected,
    apply_scales,
    calibrate_and_quantize,
    dequantize_variables,
    quantize_variables,
    quantized_fn,
    scales_host_state,
)

IMG = (4, 4, 1)


def toy_fn(variables, images):
    flat = images.reshape((images.shape[0], -1))
    return {"scores": flat @ variables["w"]}


def toy_variables(seed=0, scale=0.1):
    w = np.random.RandomState(seed).randn(16, 6).astype(np.float32) * scale
    return {"w": w}


def lower_probe(seed=3):
    f = jax.jit(lambda v, x: jnp.tanh(x @ v) + seed)
    v = np.ones((8, 8), np.float32)
    return f, v, f.lower(v, jax.ShapeDtypeStruct((4, 8), "float32"))


def journal_events(path):
    return list(read_journal(path))


# -- cache core ----------------------------------------------------------------


def test_round_trip_bit_identical(tmp_path):
    cache = ExecutableCache(str(tmp_path), registry=Registry())
    f, v, lowered = lower_probe()
    compiled, src = cache.get_or_compile(lowered, name="probe")
    assert src == "compiled"
    # a second cache object over the same dir = a fresh process's view
    cache2 = ExecutableCache(str(tmp_path), registry=Registry())
    _, _, lowered2 = lower_probe()
    loaded, src2 = cache2.get_or_compile(lowered2, name="probe")
    assert src2 == "cache"
    x = np.random.RandomState(0).rand(4, 8).astype(np.float32)
    assert np.array_equal(np.asarray(compiled(v, x)),
                          np.asarray(loaded(v, x)))


def test_key_covers_lowering_and_env(tmp_path):
    cache = ExecutableCache(str(tmp_path), registry=Registry())
    _, _, la = lower_probe(seed=3)
    _, _, lb = lower_probe(seed=4)
    assert cache.key_for(la) == cache.key_for(la.as_text())
    assert cache.key_for(la) != cache.key_for(lb)
    # a different mesh shape changes the key even for the same lowering
    other = ExecutableCache(str(tmp_path), registry=Registry(),
                            mesh_shape=(2, 4))
    assert cache.key_for(la) != other.key_for(la)


def test_load_miss_journals(tmp_path):
    j_path = str(tmp_path / "j.jsonl")
    journal = RunJournal(j_path, kind="serve")
    cache = ExecutableCache(str(tmp_path / "c"), journal=journal,
                            registry=Registry())
    _, _, lowered = lower_probe()
    assert cache.load("deadbeef" * 4, lowered, name="nope") is None
    journal.close()
    ev = [e for e in journal_events(j_path) if e["event"] == "excache_miss"]
    assert len(ev) == 1 and ev[0]["key"] == "deadbeef" * 4


@pytest.mark.parametrize("field,expected_reason", [
    ("jax", "version_skew"),
    ("jaxlib", "version_skew"),
    ("platform_version", "version_skew"),
    ("device_kind", "topology_skew"),
    ("platform", "topology_skew"),
    ("device_count", "topology_skew"),
    ("mesh_shape", "topology_skew"),
])
def test_skewed_entry_refused(tmp_path, field, expected_reason):
    j_path = str(tmp_path / "j.jsonl")
    journal = RunJournal(j_path, kind="serve")
    root = str(tmp_path / "c")
    cache = ExecutableCache(root, journal=journal, registry=Registry())
    _, v, lowered = lower_probe()
    key = cache.key_for(lowered)
    compiled, _ = cache.get_or_compile(lowered, name="probe")
    man = os.path.join(root, key + ".json")
    doc = json.load(open(man))
    doc["fingerprint"][field] = ([9, 9] if field == "mesh_shape"
                                 else 999 if field == "device_count"
                                 else "skewed-by-test")
    with open(man, "w") as fh:
        fh.write(json.dumps(doc))
    # a fresh view must refuse the entry AND fall through to the compiler
    fresh = ExecutableCache(root, journal=journal, registry=Registry())
    assert fresh.load(key, lowered, name="probe") is None
    recompiled, src = fresh.get_or_compile(lowered, name="probe")
    assert src == "compiled"
    x = np.ones((4, 8), np.float32)
    assert np.array_equal(np.asarray(compiled(v, x)),
                          np.asarray(recompiled(v, x)))
    journal.close()
    inv = [e for e in journal_events(j_path)
           if e["event"] == "excache_invalid"]
    assert [e["reason"] for e in inv] == [expected_reason] * 2
    # skewed entries stay in place (valid for the env that wrote them)
    assert not os.path.exists(os.path.join(root, "quarantine"))


def test_corrupt_payload_quarantined(tmp_path):
    j_path = str(tmp_path / "j.jsonl")
    journal = RunJournal(j_path, kind="serve")
    root = str(tmp_path / "c")
    cache = ExecutableCache(root, journal=journal, registry=Registry())
    _, _, lowered = lower_probe()
    key = cache.key_for(lowered)
    cache.get_or_compile(lowered, name="probe")
    with open(os.path.join(root, key + ".exe"), "r+b") as fh:
        fh.seek(10)
        fh.write(b"\xde\xad\xbe\xef")
    loaded, src = cache.get_or_compile(lowered, name="probe")
    assert src == "compiled"  # fell through, and...
    qdir = os.path.join(root, "quarantine")
    assert any("corrupt" in fn for fn in os.listdir(qdir))
    journal.close()
    inv = [e for e in journal_events(j_path)
           if e["event"] == "excache_invalid"]
    assert len(inv) == 1 and inv[0]["reason"] == "corrupt"
    # the fall-through re-stored a good entry: next load hits
    assert cache.load(key, lowered, name="probe") is not None


def test_corrupt_manifest_quarantined(tmp_path):
    root = str(tmp_path / "c")
    cache = ExecutableCache(root, registry=Registry())
    _, _, lowered = lower_probe()
    key = cache.key_for(lowered)
    cache.get_or_compile(lowered)
    with open(os.path.join(root, key + ".json"), "w") as fh:
        fh.write("{not json")
    assert cache.load(key, lowered) is None
    assert os.path.isdir(os.path.join(root, "quarantine"))


def test_undeserializable_payload_quarantined(tmp_path):
    root = str(tmp_path / "c")
    cache = ExecutableCache(root, registry=Registry())
    _, _, lowered = lower_probe()
    key = cache.key_for(lowered)
    cache.get_or_compile(lowered)
    # crc-VALID bytes the runtime refuses: rewrite payload + manifest crc
    import google_crc32c

    blob = pickle.dumps(("not", "an", "executable"))
    with open(os.path.join(root, key + ".exe"), "wb") as fh:
        fh.write(blob)
    man = os.path.join(root, key + ".json")
    doc = json.load(open(man))
    doc["crc32c"] = int(google_crc32c.value(blob))
    with open(man, "w") as fh:
        fh.write(json.dumps(doc))
    assert cache.load(key, lowered) is None
    qdir = os.path.join(root, "quarantine")
    assert any("deserialize_failed" in fn for fn in os.listdir(qdir))


def test_concurrent_warmers_one_dir(tmp_path):
    """N threads racing get_or_compile on one cache dir: every warmer
    gets a working executable, the dir converges to one entry, and the
    locksmith sees no ordering violations."""
    locksmith.arm(registry=Registry())
    try:
        root = str(tmp_path / "c")
        results, errors = [], []
        barrier = threading.Barrier(4)

        def warm(i):
            try:
                cache = ExecutableCache(root, registry=Registry())
                _, v, lowered = lower_probe()
                barrier.wait(timeout=30)
                compiled, src = cache.get_or_compile(lowered,
                                                     name=f"w{i}")
                x = np.ones((4, 8), np.float32)
                results.append((src, np.asarray(compiled(v, x)).sum()))
            except Exception as e:  # pragma: no cover - failure detail
                errors.append(e)

        threads = [threading.Thread(target=warm, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors
        assert len(results) == 4
        assert len({r[1] for r in results}) == 1  # identical outputs
        entries = [fn for fn in os.listdir(root) if fn.endswith(".exe")]
        assert len(entries) == 1  # last rename won, nothing torn
        report = locksmith.report()
        assert not report["violations"]
    finally:
        locksmith.disarm()


def test_reasons_in_sync_with_check_journal():
    from tools.check_journal import EXCACHE_INVALID_REASONS as SCHEMA

    assert set(EXCACHE_INVALID_REASONS) == SCHEMA


# -- engine / pool / trainer integration --------------------------------------


def test_engine_warmup_from_cache_zero_compiles(tmp_path):
    registry = Registry()
    cache = ExecutableCache(str(tmp_path), registry=registry)
    eng = Engine(registry=registry, excache=cache)
    eng.register("toy", toy_fn, toy_variables(), input_shape=IMG,
                 buckets=(1, 2))
    stats = eng.warmup()
    assert stats["cache_hits"] == 0 and stats["backend_compiles"] == 2
    # a second engine (the restarted-server model) over the same cache
    eng2 = Engine(registry=registry,
                  excache=ExecutableCache(str(tmp_path), registry=registry))
    eng2.register("toy", toy_fn, toy_variables(), input_shape=IMG,
                  buckets=(1, 2))
    c0 = recompile_count()
    stats2 = eng2.warmup()
    assert stats2["cache_hits"] == 2
    assert stats2["backend_compiles"] == 0
    assert recompile_count() == c0
    img = np.random.RandomState(1).rand(2, *IMG).astype(np.float32)
    assert np.array_equal(np.asarray(eng.run("toy", img)["scores"]),
                          np.asarray(eng2.run("toy", img)["scores"]))


def test_pool_respawn_fresh_warms_from_cache(tmp_path):
    from deep_vision_tpu.resilience import faults
    from deep_vision_tpu.resilience.retry import RetryPolicy
    from deep_vision_tpu.serve import ReplicaPool

    j_path = str(tmp_path / "j.jsonl")
    journal = RunJournal(j_path, kind="serve")
    registry = Registry()
    cache = ExecutableCache(str(tmp_path / "c"), journal=journal,
                            registry=registry)

    def build(rid):
        eng = Engine(registry=registry, journal=journal, excache=cache)
        eng.register("toy", toy_fn, toy_variables(), input_shape=IMG,
                     buckets=(1, 2))
        return eng

    pool = ReplicaPool(
        build, replicas=2, journal=journal, registry=registry,
        respawn_fresh=True, monitor_interval_s=0.05,
        respawn_policy=RetryPolicy(name="serve.replica", max_attempts=3,
                                   base_delay_s=0.01, max_delay_s=0.05))
    pool.start()
    c0 = recompile_count()
    faults.install_spec("serve.replica:io_error@1", seed=1,
                        export_env=False)
    img = np.random.RandomState(2).rand(*IMG).astype(np.float32)
    with pytest.raises(Exception):
        pool.submit("toy", img).result(timeout=60)
    faults.install(None)
    deadline = 50
    import time as _t

    for _ in range(deadline * 20):
        if all(s == "serving" for s in pool.replica_states().values()):
            break
        _t.sleep(0.05)
    assert all(s == "serving" for s in pool.replica_states().values())
    assert pool.submit("toy", img).result(timeout=60) is not None
    assert recompile_count() == c0  # the fresh engine warmed from cache
    pool.drain("close")
    journal.close()
    notes = [e for e in journal_events(j_path)
             if e.get("note") == "replica_respawn_fresh"]
    assert len(notes) == 1
    assert notes[0]["backend_compiles"] == 0
    assert notes[0]["cache_hits"] == notes[0]["pairs"] == 2


def test_trainer_cached_step(tmp_path):
    import flax.linen as nn
    import optax

    from deep_vision_tpu.train.trainer import Trainer

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=True, **kw):
            return nn.Dense(4)(x.reshape((x.shape[0], -1)))

    def loss_fn(outputs, batch):
        loss = optax.softmax_cross_entropy_with_integer_labels(
            outputs, batch["label"]).mean()
        return loss, {"loss": loss}

    j_path = str(tmp_path / "j.jsonl")
    journal = RunJournal(j_path, kind="train")
    cache = ExecutableCache(str(tmp_path / "c"), journal=journal,
                            registry=Registry())

    def make():
        return Trainer(Tiny(), optax.sgd(0.1), loss_fn,
                       jnp.ones((4, *IMG), jnp.float32),
                       executable_cache=cache, journal=journal)

    batch = {"image": np.random.RandomState(0).rand(4, *IMG)
             .astype(np.float32),
             "label": np.zeros((4,), np.int64)}
    t1 = make()
    m1 = t1.train_step(dict(batch))
    # the rebuild path: jitted wrappers + AOT table remade, the next
    # step re-lowers and must HIT the persistent cache
    t1._build_jitted_steps()
    assert t1._aot_steps == {}
    t1.train_step(dict(batch))
    # a second trainer (fresh-process model) over the same cache
    t2 = make()
    m2 = t2.train_step(dict(batch))
    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-6
    # REPEATED steps through the cache-LOADED executable: the verify
    # drive caught a segfault here — jax's serialize round trip drops
    # donation bookkeeping, so a deserialized DONATING step aliases the
    # old state's buffers (use-after-free on the second call). The
    # cache path must lower donation-free; the params must stay finite
    # across consecutive loaded-executable steps.
    for _ in range(3):
        t2.train_step(dict(batch))
    assert all(np.isfinite(np.asarray(x)).all()
               for x in jax.tree_util.tree_leaves(t2.state.params))
    journal.close()
    ev = journal_events(j_path)
    stores = [e for e in ev if e["event"] == "excache_store"]
    hits = [e for e in ev if e["event"] == "excache_hit"]
    assert len(stores) == 1  # one canonical signature, stored once
    assert len(hits) == 2  # the rebuild and the second trainer both hit
    assert all(e["name"] == "trainer/train_step" for e in stores + hits)


# -- int8 quantization --------------------------------------------------------


def test_quantize_parity_and_compression():
    variables = toy_variables(scale=0.3)
    qvars, report = quantize_variables(variables)
    assert report["quantized_leaves"] == 1
    assert report["compression"] > 3.0
    assert qvars["w"]["q8"].dtype == np.int8
    deq = dequantize_variables(qvars)
    # per-channel int8 round trip: worst-case error is scale/2 per entry
    scale = np.asarray(qvars["w"]["scale"])
    assert np.all(np.abs(np.asarray(deq["w"]) - variables["w"])
                  <= scale / 2 + 1e-7)
    x = np.random.RandomState(0).rand(4, *IMG).astype(np.float32)
    f32 = np.asarray(toy_fn(variables, x)["scores"])
    q = np.asarray(quantized_fn(toy_fn)(qvars, x)["scores"])
    assert np.allclose(f32, q, atol=0.05)


def test_quantize_refuses_kernel_free_tree():
    from deep_vision_tpu.serve import ServeError

    with pytest.raises(ServeError, match="no kernel leaves"):
        quantize_variables({"bias": np.zeros((4,), np.float32)})


def test_gate_fires_on_poisoned_calibration(tmp_path):
    """Same weights, same tolerance: a random calibration stream passes,
    the constant-image stream that exposes the cancelling-outlier
    channel REFUSES — and both verdicts are typed journal events."""
    j_path = str(tmp_path / "j.jsonl")
    journal = RunJournal(j_path, kind="serve")
    w = toy_variables(scale=0.02)
    w["w"][0, :], w["w"][1, :] = 500.0, -500.0
    rng = np.random.RandomState(0)
    random_calib = [rng.rand(4, *IMG).astype(np.float32) for _ in range(3)]
    qm = calibrate_and_quantize("toy", toy_fn, w, random_calib,
                                tolerance=0.005, journal=journal)
    assert qm.delta <= 0.005
    poison = [np.full((4, *IMG), v, np.float32) for v in (0.2, 0.6, 0.9)]
    with pytest.raises(QuantizationRejected, match="accuracy gate"):
        calibrate_and_quantize("toy", toy_fn, w, poison,
                               tolerance=0.005, journal=journal)
    journal.close()
    ev = [e for e in journal_events(j_path)
          if e["event"] == "quant_calibrated"]
    assert [e["accepted"] for e in ev] == [True, False]
    assert all(e["model"] == "toy" and isinstance(e["delta"], float)
               for e in ev)


def test_gate_refuses_empty_calibration():
    from deep_vision_tpu.serve import ServeError

    with pytest.raises(ServeError, match="at least one"):
        calibrate_and_quantize("toy", toy_fn, toy_variables(), [])


def test_int8_tree_hot_swaps_through_engine():
    """A re-calibrated int8 tree swaps through set_variables — the
    avals (int8 q8 + f32 scales) match, so the existing machinery
    accepts it without recompiling."""
    registry = Registry()
    qvars1, _ = quantize_variables(toy_variables(seed=0))
    qvars2, _ = quantize_variables(toy_variables(seed=9))
    eng = Engine(registry=registry)
    eng.register("toy", quantized_fn(toy_fn), qvars1, input_shape=IMG,
                 buckets=(2,))
    eng.warmup()
    img = np.random.RandomState(1).rand(2, *IMG).astype(np.float32)
    out1 = np.asarray(eng.run("toy", img)["scores"])
    c0 = recompile_count()
    eng.set_variables("toy", qvars2)
    out2 = np.asarray(eng.run("toy", img)["scores"])
    assert recompile_count() == c0
    assert not np.allclose(out1, out2)


def test_scales_round_trip_checkpoint_sidecar(tmp_path):
    """Scales ride the crc32c sidecar as host state; the int8 arrays
    ride the array checkpoint; apply_scales re-marries them exactly."""
    from deep_vision_tpu.core.checkpoint import CheckpointManager

    qvars, _ = quantize_variables(
        {"layer": {"kernel": np.random.RandomState(0)
                   .randn(8, 5).astype(np.float32)}})
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save_tree(1, qvars,
                  host_state={"quant_scales": scales_host_state(qvars)})
    mgr.wait()
    template = jax.tree_util.tree_map(np.zeros_like, qvars)
    restored, host = mgr.restore_tree(template, step=1)
    rejoined = apply_scales(restored, host["quant_scales"])
    assert np.array_equal(np.asarray(rejoined["layer"]["kernel"]["q8"]),
                          np.asarray(qvars["layer"]["kernel"]["q8"]))
    assert np.array_equal(
        np.asarray(rejoined["layer"]["kernel"]["scale"]),
        np.asarray(qvars["layer"]["kernel"]["scale"]))
    mgr.close()


def test_apply_scales_refuses_mismatch():
    from deep_vision_tpu.serve import ServeError

    qvars, _ = quantize_variables(toy_variables())
    host = scales_host_state(qvars)
    with pytest.raises(ServeError, match="no scales"):
        apply_scales(qvars, {})
    bad = dict(host)
    bad["w"] = bad["w"][:-1]
    with pytest.raises(ServeError, match="channels"):
        apply_scales(qvars, bad)
    extra = dict(host)
    extra["ghost"] = [1.0]
    with pytest.raises(ServeError, match="unknown leaves"):
        apply_scales(qvars, extra)


# -- satellites ---------------------------------------------------------------


def test_flash_min_tokens_env(monkeypatch):
    from deep_vision_tpu.models.vit import FLASH_MIN_TOKENS, flash_min_tokens

    monkeypatch.delenv("DVT_FLASH_MIN_TOKENS", raising=False)
    assert flash_min_tokens() == FLASH_MIN_TOKENS
    monkeypatch.setenv("DVT_FLASH_MIN_TOKENS", "2048")
    assert flash_min_tokens() == 2048
    monkeypatch.setenv("DVT_FLASH_MIN_TOKENS", "lots")
    with pytest.raises(ValueError, match="DVT_FLASH_MIN_TOKENS"):
        flash_min_tokens()


def _write_journal(tmp_path, rows):
    path = str(tmp_path / "j.jsonl")
    base = {"ts": 1.0, "run_id": "r"}
    with open(path, "w") as fh:
        fh.write(json.dumps({"event": "run_manifest", "kind": "serve",
                             "argv": [], **base}) + "\n")
        for row in rows:
            fh.write(json.dumps({**base, **row}) + "\n")
        fh.write(json.dumps({"event": "exit", "status": "clean_exit",
                             **base}) + "\n")
    return path


def test_check_journal_accepts_cold_path_events(tmp_path):
    from tools.check_journal import check_journal

    path = _write_journal(tmp_path, [
        {"event": "excache_hit", "key": "abc", "name": "m/b1"},
        {"event": "excache_miss", "key": "abc"},
        {"event": "excache_store", "key": "abc", "bytes": 10},
        {"event": "excache_invalid", "key": "abc",
         "reason": "version_skew"},
        {"event": "quant_calibrated", "model": "toy", "delta": 0.001,
         "accepted": True},
    ])
    assert check_journal(path, strict=True) == []


def test_check_journal_rejects_bad_cold_path_events(tmp_path):
    from tools.check_journal import check_journal

    path = _write_journal(tmp_path, [
        {"event": "excache_hit", "key": ""},
        {"event": "excache_invalid", "key": "abc", "reason": "dunno"},
        {"event": "quant_calibrated", "model": "toy", "delta": "big",
         "accepted": "yes"},
    ])
    errs = check_journal(path, strict=True)
    assert len(errs) == 4  # empty key, bad reason, bad delta, bad accepted


def test_obs_report_cold_path_section(tmp_path):
    from tools.obs_report import render, summarize_run

    path = _write_journal(tmp_path, [
        {"event": "excache_hit", "key": "abc"},
        {"event": "excache_invalid", "key": "abc",
         "reason": "version_skew"},
        {"event": "quant_calibrated", "model": "toy", "delta": 0.001,
         "accepted": True, "metric": "top1", "tolerance": 0.02},
    ])
    summary = summarize_run(journal_events(path))
    text = render(summary)
    assert "executable cache" in text and "version_skew" in text
    assert "int8 toy" in text and "accepted" in text
    # a journal with no cold-path events renders byte-unchanged
    plain = _write_journal(tmp_path, [])
    summary2 = summarize_run(journal_events(plain))
    assert "cold_path" not in summary2
    assert "executable cache" not in render(summary2)


def test_bench_cold_start_fields():
    import bench

    fields = bench._cold_start_fields()
    assert "warmup_compile_ms" in fields
    assert "cold_start_ms" in fields
    assert fields["warmup_compile_ms"] > 0
    # the whole point: warming from cache beats the compiler
    assert fields["cold_start_ms"] < fields["warmup_compile_ms"]


def test_preflight_check_excache(tmp_path):
    from deep_vision_tpu.tools.preflight import check_excache

    r = check_excache(str(tmp_path / "c"))
    assert r.ok, r.detail
    assert "stale entry refused" in r.detail
    # probe cleaned up after itself
    leftovers = [fn for fn in os.listdir(str(tmp_path / "c"))
                 if fn.endswith((".exe", ".json"))]
    assert leftovers == []


def test_preflight_check_excache_unwritable(tmp_path):
    from deep_vision_tpu.tools.preflight import check_excache

    # a FILE where the cache dir should be: os.makedirs fails the same
    # way a bad mount does (chmod tricks don't bind under root CI)
    not_a_dir = tmp_path / "flat"
    not_a_dir.write_text("occupied")
    r = check_excache(str(not_a_dir))
    assert not r.ok
    assert "flat" in r.detail


def test_env_fingerprint_fields():
    fp = env_fingerprint(mesh_shape=(4, 2))
    assert fp["mesh_shape"] == [4, 2]
    for field in ("jax", "jaxlib", "platform", "device_kind",
                  "device_count"):
        assert field in fp
