"""DCGAN (Radford 2015) for MNIST 28x28.

Parity targets: DCGAN/tensorflow/models.py — generator Dense(7*7*256) ->
ConvTranspose stack to 28x28x1 tanh (:30-65), discriminator two strided convs
+ dropout -> 1 logit (:8-27). Normal(0.02) init per the paper.
"""
from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from deep_vision_tpu.models import register_model
from deep_vision_tpu.nn.layers import FusedBatchNorm

_INIT = nn.initializers.normal(0.02)


class Generator(nn.Module):
    latent_dim: int = 100

    @nn.compact
    def __call__(self, z, train: bool = True):
        x = nn.Dense(7 * 7 * 256, use_bias=False, kernel_init=_INIT)(z)
        x = FusedBatchNorm(use_running_average=not train, momentum=0.9)(x)
        x = nn.leaky_relu(x, 0.2)
        x = x.reshape((-1, 7, 7, 256))
        x = nn.ConvTranspose(128, (5, 5), strides=(1, 1), padding="SAME",
                             use_bias=False, kernel_init=_INIT)(x)
        x = FusedBatchNorm(use_running_average=not train, momentum=0.9)(x)
        x = nn.leaky_relu(x, 0.2)
        x = nn.ConvTranspose(64, (5, 5), strides=(2, 2), padding="SAME",
                             use_bias=False, kernel_init=_INIT)(x)
        x = FusedBatchNorm(use_running_average=not train, momentum=0.9)(x)
        x = nn.leaky_relu(x, 0.2)
        x = nn.ConvTranspose(1, (5, 5), strides=(2, 2), padding="SAME",
                             use_bias=False, kernel_init=_INIT)(x)
        return nn.tanh(x)


class Discriminator(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(64, (5, 5), strides=(2, 2), padding="SAME", kernel_init=_INIT)(x)
        x = nn.leaky_relu(x, 0.2)
        x = nn.Dropout(0.3, deterministic=not train)(x)
        x = nn.Conv(128, (5, 5), strides=(2, 2), padding="SAME", kernel_init=_INIT)(x)
        x = nn.leaky_relu(x, 0.2)
        x = nn.Dropout(0.3, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(1, kernel_init=_INIT)(x)


@register_model("dcgan_generator")
def dcgan_generator(latent_dim: int = 100, **_):
    return Generator(latent_dim=latent_dim)


@register_model("dcgan_discriminator")
def dcgan_discriminator(**_):
    return Discriminator()
