"""Burn-rate alerting (obs/alerts.py): multi-window rule mechanics,
engine fire/resolve transitions journaled with strict-valid schemas,
the live==offline determinism contract, knob-gated default rule sets,
the /alertz + /healthz telemetry integration, and the obs_report
byte-unchanged gate for journals without goodput/alert events."""
import json
import time

from deep_vision_tpu.obs import RunJournal, read_journal
from deep_vision_tpu.obs.alerts import (
    ALERT_SEVERITIES,
    AlertEngine,
    BurnRateRule,
    WindowRule,
    _transport_bad,
    default_rules,
    default_serving_rules,
    default_training_rules,
    evaluate_journal,
)
from deep_vision_tpu.obs.registry import Registry


def tr(ts, outcome="ok", status=200, latency_ms=5.0):
    return {"event": "transport_request", "ts": ts, "run_id": "r1",
            "outcome": outcome, "status": status, "latency_ms": latency_ms,
            "deadline_ms": 1000.0}


def burn_rule(**kw):
    args = dict(classify=_transport_bad, budget=0.01, burn=2.0,
                fast_s=2.0, slow_s=8.0, min_count=4, severity="page")
    args.update(kw)
    return BurnRateRule("serve_error_burn", **args)


def drive(engine, rows):
    for r in rows:
        engine.observe(r)
    return engine


# -- BurnRateRule mechanics ---------------------------------------------------

class TestBurnRateRule:
    def test_fires_on_both_windows_then_resolves(self):
        eng = AlertEngine([burn_rule()])
        rows = [tr(t / 4.0) for t in range(5)]          # 0.0 .. 1.0 ok
        rows += [tr(1.25, "error", 500), tr(1.5, "torn", 0)]
        drive(eng, rows)
        active = eng.active()
        assert [a["rule"] for a in active] == ["serve_error_burn"]
        assert active[0]["severity"] == "page"
        assert active[0]["value"] > active[0]["threshold"] == 0.02
        assert eng.has_active_page()
        # clean traffic advances EVENT time; once the errors age out of
        # the fast window the rule stops firing and the alert resolves
        drive(eng, [tr(2.0 + t / 4.0) for t in range(9)])  # 2.0 .. 4.0
        assert eng.active() == [] and not eng.has_active_page()
        pairs = eng.pairs()
        assert len(pairs) == 1
        assert pairs[0]["rule"] == "serve_error_burn"
        assert pairs[0]["resolved_ts"] is not None
        assert pairs[0]["resolved_ts"] > pairs[0]["fired_ts"]

    def test_slow_window_guards_against_blips(self):
        # one bad in 80 ok: the FAST ratio alone would page (1/21 in
        # the last 2 s > 2%), but the slow window says the budget is
        # fine (1/81 < 2%) — no alert
        rows = [tr(t / 10.0) for t in range(80)]        # 0.0 .. 7.9
        rows.append(tr(7.95, "error", 500))
        eng = drive(AlertEngine([burn_rule()]), rows)
        assert eng.active() == []

    def test_min_count_guards_thin_fast_window(self):
        # 100% bad but only 3 samples: below min_count, no page
        rows = [tr(0.0, "error", 500), tr(0.5, "error", 500),
                tr(1.0, "error", 500)]
        eng = drive(AlertEngine([burn_rule()]), rows)
        assert eng.active() == []
        eng.observe(tr(1.5, "error", 500))  # the 4th tips it
        assert [a["rule"] for a in eng.active()] == ["serve_error_burn"]

    def test_policy_outcomes_do_not_burn_budget(self):
        # sheds / deadline refusals / 4xx are policy, not budget burn
        rows = [tr(t / 4.0, "shed", 429) for t in range(8)]
        rows += [tr(2.0 + t / 4.0, "ok", 400) for t in range(8)]
        eng = drive(AlertEngine([burn_rule()]), rows)
        assert eng.active() == []

    def test_describe_shape(self):
        d = burn_rule().describe()
        assert d["kind"] == "burn_rate" and d["name"] == "serve_error_burn"
        assert d["severity"] in ALERT_SEVERITIES


# -- WindowRule mechanics -----------------------------------------------------

class TestWindowRule:
    def _steps(self, vals, dt=1.0, field="recompiles"):
        return [{"event": "step", "ts": i * dt, "step": i, field: v}
                for i, v in enumerate(vals)]

    def test_delta_agg_catches_counter_burst(self):
        # recompiles is CUMULATIVE: max-min over the window is the burst
        rule = WindowRule("recompile_burst",
                          value=lambda r: r.get("recompiles"),
                          bound=8.0, window_s=60.0, agg="delta")
        eng = drive(AlertEngine([rule]), self._steps([2, 3, 4]))
        assert eng.active() == []
        eng.observe(self._steps([2, 3, 4, 13])[-1])
        assert [a["rule"] for a in eng.active()] == ["recompile_burst"]
        assert eng.active()[0]["value"] == 11.0
        assert not eng.has_active_page()  # ticket severity

    def test_below_direction_is_the_goodput_floor(self):
        rule = WindowRule("goodput_floor",
                          value=lambda r: r.get("goodput_frac"),
                          bound=0.8, window_s=60.0, agg="mean",
                          direction="below", min_count=1)
        rows = [{"event": "goodput_interval", "ts": 1.0,
                 "goodput_frac": 0.9},
                {"event": "goodput_interval", "ts": 2.0,
                 "goodput_frac": 0.3}]
        eng = AlertEngine([rule])
        eng.observe(rows[0])
        assert eng.active() == []
        eng.observe(rows[1])  # mean 0.6 < 0.8
        assert [a["rule"] for a in eng.active()] == ["goodput_floor"]

    def test_window_expiry_resolves(self):
        rule = WindowRule("hot", value=lambda r: r.get("v"), bound=5.0,
                          window_s=4.0, agg="max")
        eng = AlertEngine([rule])
        drive(eng, [{"event": "x", "ts": 0.0, "v": 9.0},
                    {"event": "x", "ts": 1.0, "v": 9.0}])
        assert eng.active()
        # the hot samples age out; fresh cool ones hold the window open
        drive(eng, [{"event": "x", "ts": 6.0, "v": 1.0},
                    {"event": "x", "ts": 7.0, "v": 1.0}])
        assert eng.active() == []
        assert eng.pairs()[0]["resolved_ts"] == 6.0

    def test_min_count(self):
        rule = WindowRule("hot", value=lambda r: r.get("v"), bound=5.0,
                          window_s=60.0, agg="p95", min_count=3)
        eng = drive(AlertEngine([rule]),
                    [{"event": "x", "ts": 0.0, "v": 99.0},
                     {"event": "x", "ts": 1.0, "v": 99.0}])
        assert eng.active() == []  # two samples is noise, not a signal


# -- engine transitions: journaled, schema-valid, deterministic ---------------

class TestEngine:
    def _fire_resolve_rows(self, base):
        rows = [tr(base + t / 4.0) for t in range(5)]
        rows += [tr(base + 1.25, "error", 500),
                 tr(base + 1.5, "error", 503)]
        rows += [tr(base + 2.0 + t / 4.0) for t in range(9)]
        return rows

    def test_transitions_journaled_and_strict_valid(self, tmp_path):
        from tools.check_journal import check_journal

        j = RunJournal(str(tmp_path / "run.jsonl"), kind="serve")
        j.manifest(config={"name": "t", "task": "serve"})
        eng = AlertEngine([burn_rule()], journal=j)
        j.add_tap(eng.observe)
        base = round(time.time(), 3)
        for r in self._fire_resolve_rows(base):
            j.write(r.pop("event"), **{k: v for k, v in r.items()
                                       if k != "run_id"})
        j.close()
        events = read_journal(j.path)
        fired = [e for e in events if e.get("event") == "alert_fired"]
        resolved = [e for e in events
                    if e.get("event") == "alert_resolved"]
        assert len(fired) == 1 and len(resolved) == 1
        assert fired[0]["rule"] == resolved[0]["rule"] == "serve_error_burn"
        assert fired[0]["severity"] == "page"
        assert fired[0]["value"] > fired[0]["threshold"]
        assert resolved[0]["dur_s"] > 0
        # the engine's own verdict rows are skipped on ingestion, so the
        # tap observing its own write cannot recurse or re-trigger
        assert check_journal(j.path, strict=True) == []

    def test_live_equals_offline_replay(self, tmp_path):
        """The determinism contract the fleetnet smoke asserts end to
        end: replaying the journal the live engine wrote (its own
        alert_fired/alert_resolved rows included) through a fresh
        engine reproduces the exact fired->resolved pairs."""
        j = RunJournal(str(tmp_path / "run.jsonl"), kind="serve")
        live = AlertEngine([burn_rule()], journal=j)
        j.add_tap(live.observe)
        base = round(time.time(), 3)
        for r in self._fire_resolve_rows(base):
            j.write(r.pop("event"), **{k: v for k, v in r.items()
                                       if k != "run_id"})
        j.close()
        offline = evaluate_journal(read_journal(j.path),
                                   rules=[burn_rule()])
        key = lambda pairs: [(p["rule"], p["fired_ts"], p["resolved_ts"])
                             for p in pairs]
        assert key(live.pairs()) == key(offline.pairs())
        assert len(live.pairs()) == 1

    def test_event_time_only_no_wall_clock_resolution(self):
        # frozen event time: an alert CANNOT resolve while no rows flow,
        # no matter how much wall clock passes — live and offline agree
        eng = drive(AlertEngine([burn_rule()]),
                    [tr(t / 4.0) for t in range(4)]
                    + [tr(1.25, "error", 500)])
        assert eng.active()
        assert eng.evaluate() != []  # re-evaluation at frozen event time
        assert eng.active()

    def test_clean_stream_fires_zero_alerts(self, monkeypatch):
        for k in ("DVT_ALERT_FAST_S", "DVT_ALERT_SLOW_S",
                  "DVT_ALERT_ERROR_BUDGET", "DVT_ALERT_BURN",
                  "DVT_ALERT_GOODPUT_FLOOR", "DVT_ALERT_LATENCY_BUDGET_MS",
                  "DVT_ALERT_RECOMPILE_BURST",
                  "DVT_ALERT_STARVATION_FRAC"):
            monkeypatch.delenv(k, raising=False)
        rows = [tr(t / 10.0) for t in range(100)]
        rows += [{"event": "step", "ts": 10.0 + i, "step": i,
                  "step_time_ms": 100.0, "data_wait_ms": 1.0,
                  "dispatch_ms": 50.0, "recompiles": 2}
                 for i in range(20)]
        eng = evaluate_journal(rows)  # stock knob-tuned rule set
        assert eng.active() == [] and eng.pairs() == []

    def test_gauge_tracks_active_count(self):
        reg = Registry()
        eng = AlertEngine([burn_rule()], registry=reg)
        drive(eng, [tr(t / 4.0, "error", 500) for t in range(5)])
        assert reg.gauge("alerts_active").value == 1
        drive(eng, [tr(3.0 + t / 4.0) for t in range(9)])
        assert reg.gauge("alerts_active").value == 0

    def test_alertz_shape(self):
        eng = drive(AlertEngine([burn_rule()]), [tr(0.0)])
        az = eng.alertz()
        assert az["now"] == 0.0 and az["active"] == []
        assert az["history"] == []
        assert [r["name"] for r in az["rules"]] == ["serve_error_burn"]


# -- knob-gated default rule sets ---------------------------------------------

class TestDefaultRules:
    def test_serving_always_has_the_error_burn_page(self, monkeypatch):
        monkeypatch.delenv("DVT_ALERT_LATENCY_BUDGET_MS", raising=False)
        names = [r.name for r in default_serving_rules()]
        assert names == ["serve_error_burn"]
        monkeypatch.setenv("DVT_ALERT_LATENCY_BUDGET_MS", "250")
        names = [r.name for r in default_serving_rules()]
        assert names == ["serve_error_burn", "serve_latency_budget"]

    def test_training_rules_gate_on_knobs(self, monkeypatch):
        for k in ("DVT_ALERT_GOODPUT_FLOOR",
                  "DVT_ALERT_STARVATION_FRAC"):
            monkeypatch.delenv(k, raising=False)
        monkeypatch.setenv("DVT_ALERT_RECOMPILE_BURST", "0")  # disable
        assert default_training_rules() == []
        monkeypatch.setenv("DVT_ALERT_GOODPUT_FLOOR", "0.5")
        monkeypatch.setenv("DVT_ALERT_RECOMPILE_BURST", "8")
        monkeypatch.setenv("DVT_ALERT_STARVATION_FRAC", "0.5")
        names = [r.name for r in default_training_rules()]
        assert names == ["goodput_floor", "recompile_burst",
                         "data_starvation"]
        assert len(default_rules()) == len(names) + len(
            default_serving_rules())


# -- telemetry integration: /alertz + the page-severity health flip -----------

class TestTelemetry:
    def test_alertz_route_and_healthz_flip(self, tmp_path):
        from tests.test_telemetry import get

        from deep_vision_tpu.obs.telemetry import TelemetryServer

        reg = Registry()
        j = RunJournal(str(tmp_path / "run.jsonl"), kind="serve")
        tele = TelemetryServer(port=0, role="serve", registry=reg,
                               journal=j, discovery_dir=str(tmp_path))
        tele.start()
        try:
            # no engine attached: pollable, empty
            code, _, body = get(tele.address, "/alertz")
            assert code == 200
            assert json.loads(body) == {"now": None, "active": [],
                                        "history": [], "rules": []}
            eng = AlertEngine([burn_rule()], journal=j)
            j.add_tap(eng.observe)
            tele.set_alerts(eng)
            code, _, body = get(tele.address, "/healthz")
            assert code == 200  # no active page: healthy
            drive(eng, [tr(t / 4.0, "error", 500) for t in range(5)])
            code, _, body = get(tele.address, "/alertz")
            az = json.loads(body)
            assert code == 200
            assert [a["rule"] for a in az["active"]] == ["serve_error_burn"]
            assert az["rules"][0]["kind"] == "burn_rate"
            # a firing page fails the "alerts" health source -> 503
            code, _, body = get(tele.address, "/healthz")
            row = json.loads(body)
            assert code == 503
            assert row["checks"]["alerts"]["paging"] == ["serve_error_burn"]
            # resolution flips it back
            drive(eng, [tr(3.0 + t / 4.0) for t in range(9)])
            code, _, _ = get(tele.address, "/healthz")
            assert code == 200
        finally:
            tele.close()
            if not j._closed:
                j.close()

    def test_obs_poll_strict_alerts_exit_and_columns(self, tmp_path, capsys):
        """The scriptable pager: obs_poll renders the gp%% + ALERTS
        columns from /statusz + /alertz and --strict-alerts turns a
        firing rule into a non-zero exit."""
        from tools import obs_poll

        from deep_vision_tpu.obs.goodput import GoodputMeter
        from deep_vision_tpu.obs.telemetry import TelemetryServer

        reg = Registry()
        j = RunJournal(str(tmp_path / "run.jsonl"), kind="serve")
        tele = TelemetryServer(port=0, role="serve", registry=reg,
                               journal=j, discovery_dir=str(tmp_path))
        tele.start()
        try:
            meter = GoodputMeter(journal=j, registry=reg)
            tele.add_status("goodput", meter.telemetry_status)
            eng = AlertEngine([burn_rule()], journal=j)
            tele.set_alerts(eng)
            assert obs_poll.main(["--run-dir", str(tmp_path),
                                  "--strict-alerts"]) == 0
            out = capsys.readouterr().out
            assert "gp " in out and "ALERTS" not in out
            drive(eng, [tr(t / 4.0, "error", 500) for t in range(5)])
            # a page flips healthz AND the strict exit; the column names
            # the firing rule so the one-liner says what is burning
            assert obs_poll.main(["--run-dir", str(tmp_path),
                                  "--strict-alerts"]) == 1
            out = capsys.readouterr().out
            assert "ALERTS serve_error_burn" in out
            assert "UNHEALTHY(alerts)" in out
        finally:
            tele.close()
            if not j._closed:
                j.close()


# -- schema drift guard + obs_report byte-unchanged gate ----------------------

class TestSchema:
    def test_severity_enum_does_not_drift(self):
        from tools.check_journal import ALERT_SEVERITIES as CJ_SEVERITIES

        assert set(ALERT_SEVERITIES) == CJ_SEVERITIES

    def test_strict_rejects_bad_alert_rows(self, tmp_path):
        from tools.check_journal import check_journal

        path = str(tmp_path / "j.jsonl")
        base = {"ts": time.time(), "run_id": "r1"}
        rows = [
            {"event": "run_manifest", "kind": "serve", "argv": [], **base},
            {"event": "alert_fired", "rule": "", "severity": "siren",
             "value": "high", "threshold": 0.1, **base},
            {"event": "alert_resolved", "rule": "r", "severity": "page",
             "dur_s": -2.0, **base},
            {"event": "exit", "status": "clean_exit", **base},
        ]
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        errs = check_journal(path, strict=True)
        assert any("severity" in e for e in errs), errs
        assert any("rule" in e for e in errs), errs
        assert any("value" in e for e in errs), errs
        assert any("dur_s" in e for e in errs), errs


class TestReportGate:
    def _base_events(self):
        base = 1000.0
        return [
            {"event": "run_manifest", "ts": base, "run_id": "r1",
             "kind": "train", "argv": []},
            {"event": "step", "ts": base + 1.0, "run_id": "r1", "step": 1,
             "step_time_ms": 100.0, "data_wait_ms": 1.0,
             "dispatch_ms": 50.0},
            {"event": "exit", "ts": base + 2.0, "run_id": "r1",
             "status": "clean_exit"},
        ]

    def test_report_without_new_events_is_unchanged(self):
        """A pre-goodput journal renders byte-identical: the summarizers
        return None, no keys attach, no section appears."""
        from tools.obs_report import (
            render,
            summarize_alerts,
            summarize_goodput,
            summarize_run,
        )

        events = self._base_events()
        assert summarize_goodput(events) is None
        assert summarize_alerts(events) is None
        out = summarize_run(events)
        assert "goodput" not in out and "alerts" not in out
        text = render(out)
        assert "goodput" not in text and "alert" not in text
        # and the gate is the ONLY thing between the two renderings: the
        # same run WITH goodput/alert rows gains exactly the new section
        rich = events[:-1] + [
            {"event": "goodput_summary", "ts": 1001.5, "run_id": "r1",
             "wall_s": 1.5, "goodput_frac": 0.8, "imbalance_frac": 0.0,
             "buckets": {"productive_step": 1.2, "overhead": 0.3}},
            {"event": "alert_fired", "ts": 1001.6, "run_id": "r1",
             "rule": "serve_error_burn", "severity": "page",
             "value": 0.5, "threshold": 0.02, "window_s": 2.0},
            {"event": "alert_resolved", "ts": 1001.9, "run_id": "r1",
             "rule": "serve_error_burn", "severity": "page",
             "dur_s": 0.3},
        ] + events[-1:]
        rich_text = render(summarize_run(rich))
        assert "goodput" in rich_text
        assert "serve_error_burn" in rich_text
        assert "resolved after 0.3 s" in rich_text

    def test_interval_only_journal_still_reports(self):
        # a SIGKILLed run leaves only interval rows — the report
        # accumulates them instead of going dark
        from tools.obs_report import summarize_goodput

        events = self._base_events()[:-1] + [
            {"event": "goodput_interval", "ts": 1001.0, "run_id": "r1",
             "dur_s": 10.0, "goodput_frac": 0.6,
             "buckets": {"productive_step": 6.0, "overhead": 4.0}},
            {"event": "goodput_interval", "ts": 1011.0, "run_id": "r1",
             "dur_s": 10.0, "goodput_frac": 0.6,
             "buckets": {"productive_step": 6.0, "overhead": 4.0}},
        ]
        g = summarize_goodput(events)
        assert g["source"] == "intervals"
        assert g["wall_s"] == 20.0
        assert abs(g["goodput_frac"] - 0.6) < 1e-9
        assert g["imbalance_frac"] < 1e-9
