"""Static cost model of a compiled XLA executable: flops, bytes, and the
collective inventory.

The perf plane's ground truth is the compiled artifact itself, not a
wall clock: `Compiled.cost_analysis()` carries XLA's own flop/byte
accounting, `memory_analysis()` the buffer budget, and the compiled HLO
text names every collective the partitioner inserted — operand shapes,
element types, and replica groups included. This module turns those
three sources into plain dicts the journal, the scaling bench, and the
regression gate can carry, with one cross-check that keeps the parser
honest: for a data-parallel training step, the summed all-reduce bytes
must equal the gradient-tree size (each device contributes its full
grad pytree to the reduction), so `predicted_allreduce_bytes` vs
`tree_bytes(grads)` is an end-to-end assertion on the whole chain —
sharding table -> partitioner -> HLO -> this parser.

Dependency-light on purpose: the HLO parser is pure regex over
`Compiled.as_text()` (no XLA proto imports), so it also digests HLO
dumped by other tools, and every extractor degrades to None/[] instead
of raising — a perf probe must never take down a warmup.
"""
from __future__ import annotations

import re
from typing import List, Optional

__all__ = [
    "COLLECTIVE_KINDS",
    "collective_inventory",
    "cost_summary",
    "hlo_text",
    "predicted_collective_bytes",
    "tree_bytes",
]

#: the collective op kinds the inventory recognizes (HLO opcode names);
#: check_journal's perf_collective enum is this tuple — keep in sync
COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

#: HLO primitive element type -> bytes per element
DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "s32": 4, "u32": 4, "s64": 8, "u64": 8,
    "f8e4m3fn": 1, "f8e4m3b11fnuz": 1, "f8e5m2": 1, "f8e4m3": 1,
    "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
    "bf16": 2, "f16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16,
}

# one typed array shape inside an HLO line: f32[64,128] / bf16[] / pred[8]
_SHAPE_RE = re.compile(r"\b([a-z]\w*)\[([0-9,]*)\]")

# an HLO instruction line defining a collective:
#   %name = <shape-or-tuple> all-reduce(...), channel_id=1, replica_groups=...
# async pairs lower to `-start`/`-done`; only the start carries the
# payload shape, so `-done` lines are skipped to avoid double counting
_COLLECTIVE_RE = re.compile(
    r"=\s*(?P<shape>\([^)]*\)|\S+)\s+"
    r"(?P<kind>" + "|".join(re.escape(k) for k in COLLECTIVE_KINDS) + r")"
    r"(?P<suffix>-start|-done)?\(")

_REPLICA_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{[^}]*(?:\},\{[^}]*)*\}\}|\[[^\]]*\]<=\[[^\]]*\])")


def _shape_bytes(shape_text: str):
    """(total_bytes, dtype, elements) summed over every typed array in
    `shape_text` (a tuple shape contributes all members)."""
    total = 0
    elements = 0
    dtype = None
    for m in _SHAPE_RE.finditer(shape_text):
        ty, dims = m.group(1), m.group(2)
        width = DTYPE_BYTES.get(ty)
        if width is None:
            continue  # token shapes (u32[] control deps) still match; sized 0-d below
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * width
        elements += n
        dtype = dtype or ty
    return total, dtype, elements


def _group_size(raw: Optional[str]) -> Optional[int]:
    """Participants per replica group, from either HLO form:
    iota `[1,8]<=[8]` (shape is [num_groups, group_size]) or the
    explicit `{{0,1},{2,3}}` list."""
    if not raw:
        return None
    if raw.startswith("[") and "<=" in raw:
        dims = raw[1:raw.index("]")].split(",")
        try:
            return int(dims[-1])
        except (ValueError, IndexError):
            return None
    if raw.startswith("{{"):
        first = raw[2:raw.index("}", 2)]
        return len([t for t in first.split(",") if t.strip() != ""])
    return None


def collective_inventory(hlo: str) -> List[dict]:
    """Every collective instruction in compiled HLO text, one dict each:

        {"kind", "dtype", "bytes", "elements", "group_size",
         "replica_groups", "channel_id", "op_name"}

    `bytes` is the per-device payload (sum over tuple operands).
    Unparseable lines are skipped, never fatal.
    """
    out: List[dict] = []
    for line in hlo.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if m is None or m.group("suffix") == "-done":
            continue
        nbytes, dtype, elements = _shape_bytes(m.group("shape"))
        if nbytes <= 0:
            continue
        rg = _REPLICA_GROUPS_RE.search(line)
        ch = re.search(r"channel_id=(\d+)", line)
        op = re.search(r'op_name="([^"]*)"', line)
        out.append({
            "kind": m.group("kind"),
            "dtype": dtype,
            "bytes": int(nbytes),
            "elements": int(elements),
            "group_size": _group_size(rg.group(1) if rg else None),
            "replica_groups": rg.group(1) if rg else None,
            "channel_id": int(ch.group(1)) if ch else None,
            "op_name": op.group(1) if op else None,
        })
    return out


def predicted_collective_bytes(inventory: List[dict],
                               kind: Optional[str] = None) -> int:
    """Summed per-device payload bytes over the inventory (one `kind`,
    or every collective when kind is None)."""
    return sum(c["bytes"] for c in inventory
               if kind is None or c["kind"] == kind)


def hlo_text(compiled) -> Optional[str]:
    """Compiled HLO text of an executable, or None when the backend
    doesn't expose it (never raises)."""
    try:
        txt = compiled.as_text()
        return txt if isinstance(txt, str) and txt else None
    except Exception:
        return None


def cost_summary(compiled) -> dict:
    """XLA's own accounting for one compiled executable:

        {"flops", "bytes_accessed", "argument_bytes", "output_bytes",
         "temp_bytes", "generated_code_bytes"}

    cost_analysis() keys are per-device under SPMD; older jax returns a
    one-element list. Missing analyses leave fields as None — a probe,
    not a requirement.
    """
    out = {"flops": None, "bytes_accessed": None, "argument_bytes": None,
           "output_bytes": None, "temp_bytes": None,
           "generated_code_bytes": None}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca.get("flops", -1) >= 0:
            out["flops"] = float(ca["flops"])
        ba = ca.get("bytes accessed")
        if ba is not None and ba >= 0:
            out["bytes_accessed"] = float(ba)
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        out["argument_bytes"] = int(ma.argument_size_in_bytes)
        out["output_bytes"] = int(ma.output_size_in_bytes)
        out["temp_bytes"] = int(ma.temp_size_in_bytes)
        out["generated_code_bytes"] = int(ma.generated_code_size_in_bytes)
    except Exception:
        pass
    return out


def tree_bytes(tree) -> int:
    """Total bytes of every array leaf in a pytree (the gradient-tree
    size the all-reduce inventory is checked against)."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        n = 1
        for d in shape:
            n *= int(d)
        total += n * np.dtype(dtype).itemsize
    return int(total)
