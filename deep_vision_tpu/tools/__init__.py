"""Offline dataset tooling: converters to sharded record files.

Replaces the reference's `Datasets/` scripts (VOC2007/VOC2012/MSCOCO/MPII
Ray-parallel TFRecord builders, the 710-line threaded ImageNet converter,
CycleGAN's single-file builder) with one process-parallel fan-out
(`converters.build_shards`) plus per-dataset Example builders that write the
SAME field names the reference's schemas use — shards are interchangeable.
"""
from deep_vision_tpu.tools.converters import (
    build_shards,
    chunkify,
    coco_annotations,
    cyclegan_examples,
    imagenet_annotations,
    mpii_annotations,
    voc_annotations,
)

__all__ = [
    "build_shards",
    "chunkify",
    "coco_annotations",
    "cyclegan_examples",
    "imagenet_annotations",
    "mpii_annotations",
    "voc_annotations",
]
