"""Two-process jax.distributed smoke test (VERDICT r1 weak #7).

Launches two REAL processes that `jax.distributed.initialize` against a
local coordinator on the CPU backend (2 virtual devices each), build the
global mesh, assemble a host-sharded global batch, and psum across the whole
cluster — validating `parallel/multihost.py` beyond the single-process no-op
path. This is the closest a single machine gets to a DCN-connected pod:
process boundaries and the coordinator service are real, only the transport
is local.
"""
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # two real processes: excluded from the fast tier (`-m "not slow"`)

_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
pid = int(sys.argv[1])
import jax
import numpy as np

from deep_vision_tpu.parallel import multihost as mh

mh.initialize_distributed(
    coordinator_address="127.0.0.1:%PORT%", num_processes=2, process_id=pid
)
assert mh.process_count() == 2, mh.process_count()
assert mh.process_index() == pid
assert mh.is_primary() == (pid == 0)

mesh = mh.global_mesh()
assert mesh.shape["data"] == 4, mesh.shape  # 2 hosts x 2 virtual devices

# host-sharded input: this host contributes rows [2*pid, 2*pid+1]
shard_index, num_shards = mh.host_shard()
assert (shard_index, num_shards) == (pid, 2)
local = {"x": np.asarray([2.0 * pid, 2.0 * pid + 1.0], np.float32)}
gb = mh.form_global_array(local, mesh)
assert gb["x"].shape == (4,)

# a cluster-wide collective must see every host's rows: sum(0..3) == 6
from jax.sharding import NamedSharding, PartitionSpec as P

@jax.jit
def total(x):
    return jax.numpy.sum(x)

out = float(total(gb["x"]))
assert out == 6.0, out
assert mh.per_host_batch_size(8) == 4

mh.sync_hosts("test-barrier")

# preemption consensus: only host 0 raises the flag; BOTH must act on it
# (the trainer's SIGTERM path deadlocks if hosts disagree on the step)
assert mh.agree_flag(pid == 0) is True
assert mh.agree_flag(False) is False

print(f"proc {pid} OK total={out}")
"""


def test_two_process_distributed_psum(tmp_path):
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = _WORKER.replace("%PORT%", str(port))
    path = tmp_path / "worker.py"
    path.write_text(script)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(path), str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=150)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert f"proc {pid} OK total=6.0" in out
