"""Chaos smoke: a tiny CPU train run under injected faults, then validate.

    PYTHONPATH=. python tools/chaos_run.py [--workdir artifacts/chaos_smoke]

The CI teeth behind the resilience/ contracts (`make chaos-smoke`), the
way obs-smoke is the teeth behind the obs/ schemas. Three phased runs of
a record-backed LeNet-scale train (tiny synthetic shards written on the
fly), each a real `train_cli.main()` subprocess:

  1. bad-data     `data.read:io_error@0.02` with a bad-record budget:
                  the run must COMPLETE, every skipped record must land
                  in the dead-letter JSONL with file+offset, the skip
                  count must sit within budget, and the journal must
                  pass `check_journal --strict` (typed `fault` +
                  `data_skip` events included).
  2. torn-save    `ckpt.sidecar:corrupt@2;ckpt.sidecar:crash_after_write@3`:
                  epoch 2's sidecar is bit-flipped after checksumming
                  (storage rot) and epoch 3's save is SIGKILLed inside
                  the torn-write window. The run must die by SIGKILL —
                  that is the injected preemption.
  3. resume       same checkpoint dir, no faults: `resume()` must
                  QUARANTINE the corrupt/incomplete steps (typed
                  `ckpt_quarantine` events), fall back to the newest
                  valid one, and train to completion.

Plus a no-fault overhead probe: with no spec installed, an injection
point is one module-global load + None check — the probe times it and
fails if it ever becomes measurable against a step budget.

Exit status 0 = every phase held; 1 = a resilience contract is broken.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time
from typing import List, Optional

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

CONFIG = "lenet5_chaos"
SCHEMA = "chaos_mnist"
EPOCHS = 3
TRAIN_RECORDS_PER_SHARD = 96
TRAIN_SHARDS = 2
VAL_RECORDS = 48
# one module-global load + None check; 2us would already be absurd
MAX_DISABLED_FIRE_NS = 2000.0


def register_chaos_config() -> None:
    """Register the records-backed tiny config + raw-image schema the
    chaos children train with (kept out of the production registry: only
    chaos_run processes ever see it)."""
    import numpy as np

    from deep_vision_tpu.configs import ExperimentConfig, register_config
    from deep_vision_tpu.data import datasets

    def chaos_mnist_schema(feats):
        img = np.frombuffer(feats["image/raw"][0], np.uint8).reshape(28, 28, 1)
        return {"image": img, "label": np.int32(feats["image/class/label"][0])}

    datasets.SCHEMAS.setdefault(SCHEMA, chaos_mnist_schema)
    if CONFIG not in __import__(
            "deep_vision_tpu.configs", fromlist=["CONFIG_REGISTRY"]
    ).CONFIG_REGISTRY:
        register_config(ExperimentConfig(
            name=CONFIG, task="classification", model="lenet5",
            input_shape=(32, 32, 1), num_classes=10, batch_size=16,
            epochs=EPOCHS,
            optimizer={"name": "adam", "learning_rate": 1e-3},
            dataset={"kind": "records", "schema": SCHEMA},
        ))


def child_main(argv: List[str]) -> int:
    """`chaos_run.py --child <train args...>`: a normal train_cli run with
    the chaos config registered first."""
    register_chaos_config()
    from deep_vision_tpu.train_cli import main

    return main(argv)


# -- parent-side helpers ------------------------------------------------------

def write_shards(data_dir: str) -> None:
    import numpy as np

    from deep_vision_tpu.data.example_codec import encode_example
    from deep_vision_tpu.data.records import write_records

    os.makedirs(data_dir, exist_ok=True)
    rng = np.random.RandomState(0)

    def example(label: int) -> bytes:
        img = rng.randint(0, 256, size=(28, 28, 1), dtype=np.uint8)
        return encode_example({
            "image/raw": [img.tobytes()],
            "image/class/label": [label],
        })

    for s in range(TRAIN_SHARDS):
        write_records(
            os.path.join(data_dir, f"train-{s:05d}"),
            [example(i % 10) for i in range(TRAIN_RECORDS_PER_SHARD)],
        )
    write_records(
        os.path.join(data_dir, "val-00000"),
        [example(i % 10) for i in range(VAL_RECORDS)],
    )


def run_child(train_args: List[str], log_path: str,
              timeout: float = 600.0) -> int:
    env = dict(os.environ, PYTHONPATH=ROOT, JAX_PLATFORMS="cpu")
    # a parent-installed spec must never leak into a child that did not
    # ask for one (phase 3 resumes WITHOUT faults)
    env.pop("DVT_FAULT_SPEC", None)
    env.pop("DVT_FAULT_SEED", None)
    with open(log_path, "w") as log:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"]
            + train_args,
            cwd=ROOT, env=env, stdout=log, stderr=subprocess.STDOUT,
            timeout=timeout,
        )
    return proc.returncode


def read_jsonl(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # a torn final line is the crash phases' signature
    return out


def check_journal_strict(path: str) -> bool:
    rc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "check_journal.py"),
         path, "--strict"],
        cwd=ROOT, env=dict(os.environ, PYTHONPATH=ROOT),
    ).returncode
    return rc == 0


class Failures:
    def __init__(self):
        self.errors: List[str] = []

    def check(self, ok: bool, what: str) -> bool:
        print(("  ok  " if ok else "  FAIL") + f"  {what}")
        if not ok:
            self.errors.append(what)
        return ok


def probe_disabled_overhead() -> float:
    """ns per faults.fire() call with no spec installed."""
    from deep_vision_tpu.resilience import faults

    assert faults.installed() is None
    n = 200_000
    fire = faults.fire
    t0 = time.perf_counter()
    for _ in range(n):
        fire("data.read")
    return (time.perf_counter() - t0) / n * 1e9


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "--child":
        return child_main(argv[1:])

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--workdir", default="artifacts/chaos_smoke")
    args = p.parse_args(argv)

    work = os.path.abspath(args.workdir)
    shutil.rmtree(work, ignore_errors=True)
    os.makedirs(work)
    data_dir = os.path.join(work, "data")
    write_shards(data_dir)
    f = Failures()

    # -- phase 1: bad data under budget ---------------------------------
    print("phase 1: data.read:io_error@0.02 under a bad-record budget")
    ckpt1 = os.path.join(work, "ckpt_bad_data")
    j1 = os.path.join(work, "journal_bad_data.jsonl")
    dead = os.path.join(work, "dead_letter.jsonl")
    rc = run_child(
        ["-m", CONFIG, "--data-dir", data_dir, "--epochs", str(EPOCHS),
         "--ckpt-dir", ckpt1, "--journal", j1,
         "--fault-spec", "data.read:io_error@0.02", "--fault-seed", "7",
         "--bad-record-budget", "50", "--dead-letter", dead],
        os.path.join(work, "phase1.log"),
    )
    f.check(rc == 0, f"bad-data run completed (rc={rc})")
    skips = read_jsonl(dead)
    f.check(len(skips) >= 1, f"dead-letter has skipped records ({len(skips)})")
    f.check(len(skips) <= 50, f"skips within budget ({len(skips)} <= 50)")
    f.check(all("path" in s and "offset" in s and "reason" in s
                for s in skips), "dead-letter rows carry path+offset+reason")
    ev1 = {e.get("event") for e in read_jsonl(j1)}
    f.check("fault" in ev1 and "data_skip" in ev1,
            f"journal carries typed fault + data_skip events ({sorted(ev1)})")
    f.check(check_journal_strict(j1), "check_journal --strict accepts journal")

    # -- phase 2: rot one sidecar, SIGKILL inside the next torn window --
    print("phase 2: sidecar rot + SIGKILL mid-checkpoint-save")
    ckpt2 = os.path.join(work, "ckpt_crash")
    j2 = os.path.join(work, "journal_crash.jsonl")
    rc = run_child(
        ["-m", CONFIG, "--data-dir", data_dir, "--epochs", str(EPOCHS),
         "--ckpt-dir", ckpt2, "--journal", j2,
         "--fault-spec",
         "ckpt.sidecar:corrupt@2;ckpt.sidecar:crash_after_write@3"],
        os.path.join(work, "phase2.log"),
    )
    f.check(rc == -signal.SIGKILL,
            f"run died by injected SIGKILL mid-save (rc={rc})")
    f.check(any(e.get("event") == "fault" and e.get("kind") == "corrupt"
                for e in read_jsonl(j2)),
            "journal recorded the injected sidecar corruption")

    # -- phase 3: resume must quarantine and fall back ------------------
    print("phase 3: resume quarantines the torn steps and recovers")
    j3 = os.path.join(work, "journal_resume.jsonl")
    rc = run_child(
        ["-m", CONFIG, "--data-dir", data_dir, "--epochs", str(EPOCHS),
         "--ckpt-dir", ckpt2, "-c", ckpt2, "--journal", j3],
        os.path.join(work, "phase3.log"),
    )
    f.check(rc == 0, f"resume run completed (rc={rc})")
    ev3 = read_jsonl(j3)
    quarantined = [e for e in ev3 if e.get("event") == "ckpt_quarantine"]
    f.check(len(quarantined) >= 1,
            f"resume quarantined the corrupt step(s) ({len(quarantined)})")
    f.check(os.path.isdir(os.path.join(ckpt2, "quarantine")),
            "quarantined artifacts preserved under ckpt/quarantine/")
    f.check(any(e.get("event") == "note" and e.get("note") == "resumed"
                and e.get("step", 0) > 0 for e in ev3),
            "resume restored a non-zero fallback step")
    f.check(check_journal_strict(j3), "check_journal --strict accepts journal")

    # -- disabled-injection overhead ------------------------------------
    ns = probe_disabled_overhead()
    f.check(ns < MAX_DISABLED_FIRE_NS,
            f"disabled injection point costs {ns:.0f}ns/call "
            f"(< {MAX_DISABLED_FIRE_NS:.0f}ns)")

    if f.errors:
        print(f"\nchaos-smoke: {len(f.errors)} contract(s) BROKEN "
              f"(artifacts in {work})")
        return 1
    print(f"\nchaos-smoke: all resilience contracts held (artifacts in {work})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
