"""TFRecord-compatible record container IO (no TensorFlow dependency).

The shard files every reference converter writes
(`Datasets/VOC2007/tfrecords.py:110-121`, `Datasets/MSCOCO/tfrecords.py`,
`build_imagenet_tfrecord.py`) use the TFRecord framing:

    uint64 length | uint32 masked_crc32c(length) | data | uint32 masked_crc32c(data)

crc32c comes from `google_crc32c` (C extension) so the Python reader sustains
record throughput; a C++ reader (`native/`) is the fast path for training.

Degradation contract (the Varuna/Check-N-Run posture: at production scale
SOME shard always has a rotten byte): `read_records` keeps its strict
raise-on-corruption semantics (native-reader parity), while
`read_records_tolerant` + `BadRecordBudget` skip bad records under a
bounded budget — each skip is appended to a dead-letter JSONL with
file + byte offset + reason, and the run aborts with a clear
`BadRecordBudgetExceeded` once the budget is spent. Because a record's
data CRC sits behind an intact length header, data corruption is
resyncable (skip exactly that record); a corrupt *header* loses the
framing, so the shard remainder is dead-lettered as one event rather
than risking garbage frames.
"""
from __future__ import annotations

import glob as _glob
import json
import os
import random
import struct
import sys
import time
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple, Union

import google_crc32c

from deep_vision_tpu.obs import locksmith
from deep_vision_tpu.resilience import RetryPolicy, faults

_MASK_DELTA = 0xA282EAD8


def _masked_crc(data: bytes) -> int:
    crc = google_crc32c.value(data)
    return ((crc >> 15 | crc << 17) + _MASK_DELTA) & 0xFFFFFFFF


class RecordWriter:
    """Append-only TFRecord-framing writer."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "wb")

    def write(self, record: bytes) -> None:
        header = struct.pack("<Q", len(record))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(record)
        self._f.write(struct.pack("<I", _masked_crc(record)))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_records(path: str, records: Iterable[bytes]) -> int:
    n = 0
    with RecordWriter(path) as w:
        for r in records:
            w.write(r)
            n += 1
    return n


def read_records(path: str, verify: bool = True) -> Iterator[bytes]:
    """Yield raw record payloads from one file (strict: corruption raises).

    `faults.fire("data.read")` is the chaos-test hook; it costs one global
    None-check per record when no fault spec is installed."""
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                return
            if len(header) < 8:
                raise EOFError(f"truncated record header in {path}")
            (length,) = struct.unpack("<Q", header)
            (hcrc,) = struct.unpack("<I", f.read(4))
            if verify and _masked_crc(header) != hcrc:
                raise IOError(f"corrupt record header in {path}")
            data = f.read(length)
            if len(data) < length:
                raise EOFError(f"truncated record in {path}")
            (dcrc,) = struct.unpack("<I", f.read(4))
            if verify and _masked_crc(data) != dcrc:
                raise IOError(f"corrupt record in {path}")
            faults.fire("data.read")
            yield data


# -- bounded-degradation reading ---------------------------------------------

class BadRecordBudgetExceeded(RuntimeError):
    """The run's tolerance for bad records is spent; aborting is now the
    correct behavior (silent unbounded skipping would train on a silently
    shrinking dataset)."""


class BadRecordBudget:
    """Counts skipped records against a bound and dead-letters each one.

    max_count:     absolute cap on skipped records (None = uncapped).
    max_fraction:  cap on bad/seen, enforced once `min_seen` records have
                   been observed (a fraction over 3 records is noise).
    dead_letter_path: JSONL, one line per skipped record with file, byte
                   offset, reason, and timestamp. Appended with O_APPEND
                   per line so worker processes can share one file.
    journal:       obs.RunJournal for typed `data_skip` events (dropped on
                   pickling — spawned workers keep the dead-letter file and
                   counters, the parent keeps the journal).

    Thread-safe; picklable (DataLoader worker processes receive a copy, so
    with `num_procs > 0` the bound applies per worker — the global worst
    case is num_procs * budget, documented in the README).
    """

    def __init__(self, max_count: Optional[int] = None,
                 max_fraction: Optional[float] = None,
                 min_seen: int = 100,
                 dead_letter_path: Optional[str] = None,
                 journal=None):
        if max_count is None and max_fraction is None:
            raise ValueError("budget needs max_count and/or max_fraction")
        self.max_count = max_count
        self.max_fraction = max_fraction
        self.min_seen = min_seen
        self.dead_letter_path = dead_letter_path
        self.journal = journal
        self.bad = 0
        self.ok = 0
        # snapshot-resume replay latch (data/snapshot.py): while True,
        # record_bad still COUNTS (the deterministic replay must re-spend
        # the epoch's budget to land on the saved position) but skips the
        # dead-letter row, journal event, and stderr line — the original
        # run already emitted them for this prefix
        self.replaying = False
        self._lock = locksmith.lock("data.records.budget")

    @classmethod
    def parse(cls, spec: str, **kw) -> "BadRecordBudget":
        """CLI form: a value < 1 is a fraction, >= 1 an absolute count."""
        v = float(spec)
        if v <= 0:
            raise ValueError(f"bad-record budget must be positive, got {spec}")
        if v < 1.0:
            return cls(max_fraction=v, **kw)
        return cls(max_count=int(v), **kw)

    def __getstate__(self):
        d = dict(self.__dict__)
        d["journal"] = None
        d["_lock"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._lock = locksmith.lock("data.records.budget")

    def describe(self) -> str:
        parts = []
        if self.max_count is not None:
            parts.append(f"max_count={self.max_count}")
        if self.max_fraction is not None:
            parts.append(f"max_fraction={self.max_fraction}")
        return " ".join(parts)

    def record_ok(self, n: int = 1) -> None:
        with self._lock:
            self.ok += n

    def spend(self) -> dict:
        """The current (bad, ok) counters, for the pipeline snapshot."""
        with self._lock:
            return {"bad": self.bad, "ok": self.ok}

    def set_spend(self, spend: dict) -> None:
        """Restore counters from a snapshot (data/snapshot.py resume)."""
        with self._lock:
            self.bad = int(spend.get("bad", 0))
            self.ok = int(spend.get("ok", 0))

    def _exceeded(self) -> bool:
        if self.max_count is not None and self.bad > self.max_count:
            return True
        seen = self.bad + self.ok
        return (self.max_fraction is not None and seen >= self.min_seen
                and self.bad / seen > self.max_fraction)

    def record_bad(self, path: str, offset: int, reason: str) -> None:
        """Account one skipped record; raises once the budget is spent."""
        with self._lock:
            self.bad += 1
            bad = self.bad
        if self.replaying:
            # snapshot replay: count silently (see __init__), still abort
            # once spent — a budget the original run exhausted must not
            # survive the resume
            if self._exceeded():
                raise BadRecordBudgetExceeded(
                    f"bad-record budget exceeded during snapshot replay "
                    f"({self.describe()}): {self.bad} bad of "
                    f"{self.bad + self.ok} seen")
            return
        row = {"ts": round(time.time(), 3), "path": path,
               "offset": int(offset), "reason": reason}
        if self.dead_letter_path:
            d = os.path.dirname(self.dead_letter_path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(self.dead_letter_path, "a") as f:
                f.write(json.dumps(row) + "\n")
        try:
            from deep_vision_tpu.obs.registry import get_registry

            get_registry().counter(
                "data_bad_records_total", "records skipped as bad").inc()
        except Exception:
            pass
        if self.journal is not None:
            self.journal.write("data_skip", **row)
        # first few loudly, then every 100th: a rotting dataset must be
        # visible in the log without drowning it
        if bad <= 5 or bad % 100 == 0:
            print(f"data: SKIPPED bad record #{bad} at {path}:{offset} "
                  f"({reason})"
                  + (f" -> {self.dead_letter_path}"
                     if self.dead_letter_path else ""),
                  file=sys.stderr)
        if self._exceeded():
            raise BadRecordBudgetExceeded(
                f"bad-record budget exceeded ({self.describe()}): "
                f"{self.bad} bad of {self.bad + self.ok} seen; last: "
                f"{path}:{offset} ({reason})"
                + (f"; full list in {self.dead_letter_path}"
                   if self.dead_letter_path else ""))


# shard opens retry transient I/O (flaky network filesystems); corruption
# inside the file is the budget's job, not the retry's
_OPEN_RETRY = RetryPolicy(name="data.open", max_attempts=3,
                          base_delay_s=0.2, max_delay_s=2.0)


def read_records_tolerant(
    path: str, budget: BadRecordBudget, verify: bool = True
) -> Iterator[Tuple[int, bytes]]:
    """Yield (byte_offset, payload), skipping bad records under `budget`.

    Data-CRC corruption is resyncable (the length header framed the record)
    and skips exactly one record; a corrupt/truncated header loses the
    framing, so the shard remainder is dead-lettered as ONE budget event.
    `BadRecordBudgetExceeded` propagates to the caller — that is the abort.
    """
    with _OPEN_RETRY.call(open, path, "rb") as f:
        while True:
            offset = f.tell()
            header = f.read(8)
            if not header:
                return
            if len(header) < 8:
                budget.record_bad(path, offset, "truncated record header")
                return
            (length,) = struct.unpack("<Q", header)
            hcrc_b = f.read(4)
            if len(hcrc_b) < 4 or (
                    verify and _masked_crc(header) != struct.unpack(
                        "<I", hcrc_b)[0]):
                budget.record_bad(
                    path, offset,
                    "corrupt record header (framing lost; skipping the "
                    "shard remainder)")
                return
            data = f.read(length)
            dcrc_b = f.read(4)
            if len(data) < length or len(dcrc_b) < 4:
                budget.record_bad(path, offset, "truncated record")
                return
            if verify and _masked_crc(data) != struct.unpack("<I", dcrc_b)[0]:
                budget.record_bad(path, offset, "corrupt record data")
                continue
            try:
                faults.fire("data.read")
            except IOError as e:
                budget.record_bad(path, offset, f"read fault: {e}")
                continue
            yield offset, data
            budget.record_ok()


def best_reader():
    """The fastest available single-file record reader: the native C++ one
    (native/libdvtpu.so, GIL-free IO+CRC) when built, else `read_records`.
    Both have identical iteration order and exception behavior."""
    try:
        from deep_vision_tpu.data.native import (
            native_available,
            read_records_native,
        )

        if native_available():
            return read_records_native
    except Exception:
        pass
    return read_records


def expand_shards(pattern: Union[str, Sequence[str]]) -> List[str]:
    """Glob pattern(s) -> sorted shard list (list_files analog, deterministic)."""
    patterns = [pattern] if isinstance(pattern, str) else list(pattern)
    files: List[str] = []
    for p in patterns:
        matched = sorted(_glob.glob(p)) if any(c in p for c in "*?[") else [p]
        files.extend(matched)
    if not files:
        raise FileNotFoundError(f"no record shards match {pattern!r}")
    return files


def record_iterator(
    pattern: Union[str, Sequence[str]],
    *,
    shuffle_shards: bool = False,
    seed: Optional[int] = None,
    shard_index: int = 0,
    num_shards: int = 1,
) -> Iterator[bytes]:
    """Iterate records across shards.

    `shard_index/num_shards` split the *file list* across hosts — the
    host-sharded input feed for multi-host training (each host reads only its
    shard subset, the pjit analog of `experimental_distribute_dataset` at
    YOLO/tensorflow/train.py:291-294).
    """
    files = expand_shards(pattern)
    files = files[shard_index::num_shards]
    if shuffle_shards:
        random.Random(seed).shuffle(files)
    reader = best_reader()
    for path in files:
        yield from reader(path)
