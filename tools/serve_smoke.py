"""Serve smoke: a real multi-model CPU server proving the serving contracts.

    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/serve_smoke.py \
        [--workdir artifacts/serve_smoke]

The CI teeth behind serve/ (`make serve-smoke`, a `make verify`
prerequisite), the way obs-smoke gates obs/ and chaos-smoke gates
resilience/. One in-process server routes the REAL YOLO + Hourglass-pose
predictors (64x64, tiny heads) over one CPU device, then a subprocess
proves the preemption path:

  1. warmup       every (model, bucket) pair AOT-compiles at startup;
                  the backend-compile counter delta must equal the
                  warmed pair count exactly (nothing eager slipped in).
  2. mixed load   bursts of 1..4 concurrent requests per model — every
                  batch rounds to a warmed bucket, every response checks
                  out, and the recompile counter must not move AT ALL.
  3. chaos        `data.read:io_error@N` injected at the request-decode
                  boundary: exactly one request fails with the injected
                  error, everyone else (including requests submitted
                  after) is served — request-scoped degradation.
  4. int8         the pose model calibrates and quantizes
                  (serve/quantize.py): per-channel int8 weights pass the
                  accuracy-delta gate (typed `quant_calibrated`), the
                  quantized engine serves the same traffic through its
                  own warmed server, and the SLO report prints BEFORE
                  (f32) and AFTER (int8) so the swap is a number.
  5. clean close  drain journals `serve_drain(close, flushed)`, the
                  journal passes `check_journal --strict` (serve_*
                  schemas + trace), obs_report renders the serving
                  summary, and the flight dir is EMPTY — a healthy
                  shutdown leaves no postmortem. The runtime lock
                  sanitizer (obs/locksmith.py), armed since startup,
                  must report ZERO lock-order violations.
  6. sigterm      a child server under live traffic gets SIGTERM: it
                  must flush every accepted request, journal
                  `serve_drain(sigterm, flushed)`, leave a crc-valid
                  `preempt` flight bundle, and exit 0 with a clean
                  journal terminal event.

Exit status 0 = every contract held; 1 = something broke.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
from typing import List, Optional

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from tools.smoke_util import read_jsonl  # noqa: E402

INPUT_SHAPE = (64, 64, 3)
YOLO_BUCKETS = (1, 2, 4)
POSE_BUCKETS = (1, 2, 4)
MAX_WAIT_MS = 15.0


class Failures:
    def __init__(self):
        self.errors: List[str] = []

    def check(self, ok: bool, what: str) -> bool:
        print(("  ok  " if ok else "  FAIL") + f"  {what}")
        if not ok:
            self.errors.append(what)
        return ok


def check_journal_strict(path: str, trace: Optional[str] = None) -> bool:
    cmd = [sys.executable, os.path.join(ROOT, "tools", "check_journal.py"),
           path, "--strict"]
    if trace:
        cmd += ["--trace", trace]
    return subprocess.run(
        cmd, cwd=ROOT, env=dict(os.environ, PYTHONPATH=ROOT),
    ).returncode == 0


def build_models(models=("yolo", "pose")):
    """Tiny real predictors: the zoo's YOLO decode->NMS and Hourglass
    keypoint paths at 64x64 — real enough that a recompile would show."""
    import jax
    import jax.numpy as jnp

    from deep_vision_tpu.inference import pose_predict_fn, yolo_predict_fn
    from deep_vision_tpu.models import get_model

    x = jnp.zeros((1,) + INPUT_SHAPE, jnp.float32)
    out = {}
    if "yolo" in models:
        m = get_model("yolov3", num_classes=4)
        out["yolo"] = (
            yolo_predict_fn(m, max_detections=8, score_threshold=0.3),
            m.init(jax.random.PRNGKey(0), x, train=False), YOLO_BUCKETS)
    if "pose" in models:
        m = get_model("hourglass", num_stack=1, num_heatmap=4)
        out["pose"] = (
            pose_predict_fn(m),
            m.init(jax.random.PRNGKey(1), x, train=False), POSE_BUCKETS)
    return out


def rand_image(rng):
    return rng.rand(*INPUT_SHAPE).astype("float32")


# -- child: the SIGTERM-drain server ------------------------------------------

def child_main(argv: List[str]) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--workdir", required=True)
    args = p.parse_args(argv)
    import numpy as np

    from deep_vision_tpu.obs import (
        FlightRecorder,
        RunJournal,
        locksmith,
        set_flight,
    )
    from deep_vision_tpu.serve import Engine, Server, ServerClosed

    work = args.workdir
    journal = RunJournal(os.path.join(work, "journal_sigterm.jsonl"),
                         kind="serve")
    journal.manifest(config={"name": "serve_smoke_sigterm",
                             "task": "serving"})
    flight = FlightRecorder(os.path.join(work, "flight_sigterm"),
                            run_id=journal.run_id)
    flight.attach(journal)
    set_flight(flight)
    # the lock sanitizer rides the SIGTERM-drain path too: an inversion
    # between the drain latch and the dispatchers would journal here
    locksmith.arm(journal=journal)

    engine = Engine(journal=journal)
    for name, (fn, variables, buckets) in build_models(("pose",)).items():
        engine.register(name, fn, variables, INPUT_SHAPE, buckets=(1, 2))
    engine.warmup()
    server = Server(engine, journal=journal, max_wait_ms=MAX_WAIT_MS)
    server.start()
    server.install_sigterm()

    def traffic():
        rng = np.random.RandomState(7)
        while True:
            try:
                server.submit("pose", rand_image(rng))
            except ServerClosed:
                return
            time.sleep(0.05)

    t = threading.Thread(target=traffic, name="traffic", daemon=True)
    t.start()
    print("READY", flush=True)  # the parent sends SIGTERM after this
    server.wait_for_stop()
    summary = server.drain("sigterm")
    t.join(timeout=5)
    lock_report = locksmith.report()
    locksmith.disarm()  # flushes any queued lock events into the journal
    flight.close()  # disarm the crash dump; the preempt bundle stays
    journal.close()
    print(f"drained: {summary}", flush=True)
    if lock_report["violations"]:
        print(f"locksmith: ORDER VIOLATIONS {lock_report['violations']}",
              flush=True)
        return 1
    return 0 if summary["outcome"] == "flushed" else 1


# -- parent: phases 1-4 in process, phase 5 via the child ---------------------

def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "--child":
        return child_main(argv[1:])

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--workdir", default="artifacts/serve_smoke")
    args = p.parse_args(argv)

    import numpy as np

    from deep_vision_tpu.obs import (
        FlightRecorder,
        RunJournal,
        Tracer,
        locksmith,
        set_flight,
        set_tracer,
    )
    from deep_vision_tpu.obs.stepclock import recompile_count
    from deep_vision_tpu.resilience import FaultInjected, faults
    from deep_vision_tpu.serve import Engine, Server

    work = os.path.abspath(args.workdir)
    shutil.rmtree(work, ignore_errors=True)
    os.makedirs(work)
    f = Failures()
    j_path = os.path.join(work, "journal.jsonl")
    t_path = os.path.join(work, "trace.json")
    flight_dir = os.path.join(work, "flight")

    journal = RunJournal(j_path, kind="serve")
    journal.manifest(config={"name": "serve_smoke", "task": "serving"})
    tracer = Tracer(t_path, run_id=journal.run_id)
    set_tracer(tracer)
    flight = FlightRecorder(flight_dir, run_id=journal.run_id)
    flight.attach(journal)
    set_flight(flight)
    # arm the runtime lock sanitizer for the WHOLE serving run: warmup,
    # mixed load, chaos, and drain all execute under order/hold checking,
    # and phase 4 asserts the journal carries zero lock_order_violation
    # events (obs/locksmith.py — the dynamic half of lint/concur.py)
    locksmith.arm(journal=journal)

    # -- phase 1: AOT warmup, compile accounting ------------------------
    print("phase 1: AOT warmup compiles every (model, bucket) pair")
    models = build_models()
    engine = Engine(journal=journal)
    for name, (fn, variables, buckets) in models.items():
        engine.register(name, fn, variables, INPUT_SHAPE, buckets=buckets)
    stats = engine.warmup()
    pairs = len(YOLO_BUCKETS) + len(POSE_BUCKETS)
    f.check(stats["pairs"] == pairs,
            f"warmed {stats['pairs']}/{pairs} (model, bucket) pairs")
    f.check(stats["backend_compiles"] == pairs,
            f"recompile counter delta equals the warmed bucket count "
            f"({stats['backend_compiles']} == {pairs})")

    server = Server(engine, journal=journal, max_wait_ms=MAX_WAIT_MS)
    server.start()
    rng = np.random.RandomState(0)

    # -- phase 2: mixed-size stream, zero additional compiles -----------
    print("phase 2: mixed-size request stream after warmup")
    c0 = recompile_count()
    ok = 0
    for burst in (1, 3, 2, 4, 1, 2, 4, 3):
        futs = [(model, server.submit(model, rand_image(rng)))
                for model in ("yolo", "pose") for _ in range(burst)]
        for model, fut in futs:
            row = fut.result(timeout=120)
            if model == "yolo":
                assert row["boxes"].shape == (8, 4), row["boxes"].shape
            else:
                assert row.shape == (4, 3), row.shape
            ok += 1
    f.check(ok == 2 * (1 + 3 + 2 + 4 + 1 + 2 + 4 + 3),
            f"all {ok} mixed-size requests answered with correct shapes")
    f.check(recompile_count() == c0,
            "zero additional compilations across the mixed-size stream")

    # -- phase 3: injected data.read fault degrades one request ---------
    print("phase 3: injected data.read fault is request-scoped")
    faults.install_spec("data.read:io_error@2", seed=11, journal=journal,
                        export_env=False)
    futs = [server.submit("yolo", rand_image(rng)) for _ in range(3)]
    outcomes = []
    for fut in futs:
        try:
            fut.result(timeout=120)
            outcomes.append("ok")
        except FaultInjected:
            outcomes.append("fault")
    faults.install(None)
    f.check(outcomes.count("fault") == 1 and outcomes.count("ok") == 2,
            f"exactly the injected request failed ({outcomes})")
    after = server.submit("pose", rand_image(rng)).result(timeout=120)
    f.check(after.shape == (4, 3),
            "server keeps answering after the injected fault")

    # -- phase 4: int8 calibrate -> gate -> serve -----------------------
    print("phase 4: int8 quantization passes the gate and serves "
          "(SLO before/after)")
    from deep_vision_tpu.serve.quantize import (
        QuantizationRejected,
        calibrate_and_quantize,
    )

    pose_fn, pose_vars, pose_buckets = models["pose"]
    calib = [np.stack([rand_image(rng) for _ in range(2)])
             for _ in range(4)]
    try:
        qm = calibrate_and_quantize("pose", pose_fn, pose_vars, calib,
                                    tolerance=0.02, journal=journal)
        f.check(True, f"int8 pose passed the gate ({qm.metric} delta "
                      f"{qm.delta:.2g} <= 0.02, "
                      f"{qm.report['compression']}x weight compression)")
    except QuantizationRejected as e:
        qm = None
        f.check(False, f"int8 pose refused by the gate: {e}")
    if qm is not None:
        from deep_vision_tpu.obs.registry import Registry

        # private registry: the int8 SLO must be its own numbers, not
        # the f32 histograms with more samples mixed in
        q_registry = Registry()
        q_engine = Engine(journal=journal, registry=q_registry)
        q_engine.register("pose", qm.fn, qm.variables, INPUT_SHAPE,
                          buckets=pose_buckets)
        q_engine.warmup()
        q_server = Server(q_engine, journal=journal, registry=q_registry,
                          max_wait_ms=MAX_WAIT_MS, tags={"engine": "int8"})
        q_server.start()
        for _ in range(12):
            out = q_server.submit("pose", rand_image(rng)).result(timeout=120)
            assert out.shape == (4, 3), out.shape
        q_summary = q_server.close()
        f.check(q_summary["outcome"] == "flushed",
                f"int8 server drained clean ({q_summary['completed']} "
                "served)")
        print("  SLO before (f32):")
        print("    " + server.slo.render().replace("\n", "\n    "))
        print("  SLO after (int8):")
        print("    " + q_server.slo.render().replace("\n", "\n    "))

    # -- phase 5: clean close leaves no postmortem ----------------------
    print("phase 5: clean shutdown — strict journal, no flight bundle")
    summary = server.close()
    f.check(summary["outcome"] == "flushed" and summary["pending"] == 0,
            f"close drained everything ({summary})")
    print("  " + server.slo.render().replace("\n", "\n  "))
    lock_report = locksmith.report()
    f.check(not lock_report["violations"],
            "locksmith: zero lock-order violations across warmup + load "
            "+ chaos + drain"
            + ("" if not lock_report["violations"]
               else f" ({lock_report['violations'][0]})"))
    locksmith.disarm()  # flush queued lock events before the journal closes
    tracer.close()
    set_tracer(None)
    flight.close()
    set_flight(None)
    journal.close()
    f.check(not os.listdir(flight_dir) if os.path.isdir(flight_dir)
            else True, "clean shutdown left no flight bundle")
    f.check(check_journal_strict(j_path, trace=t_path),
            "check_journal --strict accepts journal + trace "
            "(serve_* schemas)")
    rep = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "obs_report.py"),
         j_path],
        cwd=ROOT, env=dict(os.environ, PYTHONPATH=ROOT),
        stdout=subprocess.PIPE, text=True)
    f.check(rep.returncode == 0 and "serving yolo" in rep.stdout
            and "serve drain" in rep.stdout,
            "obs_report renders the serving summary")
    ev = read_jsonl(j_path)
    spans = {e.get("name") for e in
             (json.load(open(t_path)).get("traceEvents") or [])}
    f.check({"serve/warmup", "serve/batch", "serve/drain"} <= spans,
            f"serve/* trace spans recorded ({sorted(s for s in spans if str(s).startswith('serve'))})")
    f.check(any(e.get("event") == "serve_batch"
                and e.get("size", 0) < e.get("bucket", 0) for e in ev),
            "padding observed and journaled (occupancy < 100% somewhere)")
    f.check(not any(e.get("event") == "lock_order_violation" for e in ev),
            "journal carries zero lock_order_violation events")

    # -- phase 6: SIGTERM drain in a child server -----------------------
    print("phase 6: SIGTERM drain flushes in-flight requests + dumps "
          "a preempt flight bundle")
    log_path = os.path.join(work, "sigterm_child.log")
    env = dict(os.environ, PYTHONPATH=ROOT, JAX_PLATFORMS="cpu")
    env.pop("DVT_FAULT_SPEC", None)
    env.pop("DVT_FAULT_SEED", None)
    with open(log_path, "w") as log:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child",
             "--workdir", work],
            cwd=ROOT, env=env, stdout=subprocess.PIPE,
            stderr=log, text=True)
        ready = proc.stdout.readline().strip()
        f.check(ready == "READY", f"child server came up ({ready!r})")
        time.sleep(1.5)  # let live traffic flow
        proc.send_signal(signal.SIGTERM)
        try:
            out, _ = proc.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
        log.write(out or "")
    f.check(proc.returncode == 0,
            f"child drained and exited cleanly (rc={proc.returncode})")

    jc = os.path.join(work, "journal_sigterm.jsonl")
    ev = read_jsonl(jc)
    drains = [e for e in ev if e.get("event") == "serve_drain"]
    f.check(len(drains) == 1 and drains[0].get("reason") == "sigterm"
            and drains[0].get("outcome") == "flushed",
            f"serve_drain journaled sigterm/flushed ({drains})")
    if drains:
        d = drains[0]
        f.check(d.get("accepted", -1) >= 1
                and d.get("accepted") == d.get("completed", 0)
                + d.get("errors", 0) + d.get("cancelled", 0),
                f"every accepted request accounted for "
                f"(accepted={d.get('accepted')} "
                f"completed={d.get('completed')})")
    f.check(any(e.get("event") == "flight_dump"
                and e.get("reason") == "preempt"
                and e.get("outcome") == "written" for e in ev),
            "journal carries the preempt flight_dump event")
    from deep_vision_tpu.obs.flight import find_bundles, validate_bundle

    bundles = find_bundles(os.path.join(work, "flight_sigterm"))
    f.check(len(bundles) == 1 and "preempt" in os.path.basename(bundles[0]),
            f"SIGTERM left exactly one preempt bundle ({bundles})")
    if bundles:
        errs = validate_bundle(bundles[0])
        f.check(not errs, "preempt bundle structure + crc valid"
                + ("" if not errs else f" ({errs[0]})"))
    f.check(not any(e.get("event") == "lock_order_violation" for e in ev),
            "sigterm journal carries zero lock_order_violation events "
            "(locksmith armed through the drain)")
    f.check(check_journal_strict(jc),
            "check_journal --strict accepts the sigterm journal")

    if f.errors:
        print(f"\nserve-smoke: {len(f.errors)} contract(s) BROKEN "
              f"(artifacts in {work})")
        return 1
    print(f"\nserve-smoke: all serving contracts held (artifacts in {work})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
