"""Validate journal JSONL + Chrome trace JSON against the obs/ schemas.

    PYTHONPATH=. python tools/check_journal.py run.jsonl [run2.jsonl ...]
        [--trace trace.json] [--require-exit] [--strict]

The CI teeth behind obs/README.md: every event line must parse, carry
the `event`/`ts`/`run_id` envelope, and (for known event types) carry
that type's required fields. Unknown event types are tolerated by
default — a journal written by a newer producer must stay validatable
by an older checker — while `--strict` makes them violations AND
demands a clean `exit` terminal event (what `make obs-smoke` asserts
after its tiny train run: a smoke run that crashed, or that emitted an
event this schema has never heard of, is a failure even if every line
it did write was well-formed). `--require-exit` demands only the
terminal event. Trace files must be valid JSON in Trace Event Format:
a `traceEvents` list whose complete events ("ph": "X") carry
name/ts/dur/pid/tid.

Exit status 0 = all files valid; 2 = any file invalid (each violation
printed with its file:line); 64 = usage error.
"""
from __future__ import annotations

import json
import os
import re
import sys
from typing import List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deep_vision_tpu.cli import (  # noqa: E402
    EXIT_INVALID,
    EXIT_OK,
    EXIT_USAGE,
    UsageErrorParser,
)

__all__ = ["check_journal", "check_trace", "main",
           "EXIT_OK", "EXIT_INVALID", "EXIT_USAGE"]

# envelope fields on every line, then per-event required fields
ENVELOPE = ("event", "ts", "run_id")
EVENT_FIELDS = {
    "run_manifest": ("kind", "argv"),
    "step": ("step",),
    "epoch": ("epoch", "summary"),
    "eval": ("epoch", "summary"),
    "checkpoint": ("step", "saved"),
    "health": ("kind",),
    "profile": ("action",),
    "bench": ("name", "result"),
    "retry": ("name", "attempt", "error", "outcome"),
    "fault": ("point", "kind"),
    "data_skip": ("path", "offset", "reason"),
    "ckpt_quarantine": ("step", "reason"),
    "backend_lost": ("attempt", "error", "kind"),
    "backend_recovered": ("attempt",),
    "preempt_checkpoint": ("step", "saved"),
    "profile_capture": ("reason", "outcome"),
    "flight_dump": ("reason", "dir", "outcome"),
    "straggler": ("step", "gap_ms", "host"),
    "serve_request": ("model", "latency_ms", "outcome"),
    "serve_batch": ("model", "bucket", "size"),
    "serve_drain": ("reason", "outcome", "accepted", "completed"),
    "serve_shed": ("model", "reason"),
    "serve_swap": ("phase", "outcome"),
    "replica_lost": ("replica", "attempt"),
    "replica_recovered": ("replica", "attempt"),
    "lock_order_violation": ("lock_a", "lock_b", "thread"),
    "lock_contention": ("lock", "kind", "ms"),
    "data_resume": ("verdict", "epoch", "batches"),
    "data_worker_lost": ("worker", "attempt"),
    "data_worker_recovered": ("worker", "attempt"),
    "data_service": ("role", "batches"),
    "excache_hit": ("key",),
    "excache_miss": ("key",),
    "excache_store": ("key",),
    "excache_invalid": ("key", "reason"),
    "quant_calibrated": ("model", "delta", "accepted"),
    "sharding_resolved": ("model", "matched", "unmatched",
                          "sharded_leaves", "mesh"),
    "host_lost": ("host", "generation"),
    "host_joined": ("host", "generation"),
    "world_resized": ("from", "to", "generation", "resume_step"),
    "data_reshard": ("generation", "from", "to"),
    "note": (),
    "exit": ("status",),
    "crash": ("reason",),
    "telemetry_server": ("host", "port", "outcome"),
    "transport_request": ("status", "deadline_ms", "outcome"),
    "transport_server": ("host", "port", "outcome"),
    "perf_profile": ("name", "collective_count", "collective_bytes"),
    "perf_collective": ("name", "kind", "dtype", "ops", "bytes"),
    "perf_regression": ("metric", "baseline", "observed", "threshold"),
    "goodput_interval": ("dur_s", "buckets"),
    "goodput_summary": ("wall_s", "buckets", "goodput_frac",
                        "imbalance_frac"),
    "alert_fired": ("rule", "severity", "value", "threshold"),
    "alert_resolved": ("rule", "severity", "dur_s"),
}
HEALTH_KINDS = {"non_finite", "loss_spike", "divergence", "hang",
                "watchdog_started"}
RETRY_OUTCOMES = {"retrying", "gave_up", "recovered"}
PROFILE_CAPTURE_REASONS = {"static_window", "step_time_z", "data_wait_z",
                           "recompile_burst", "hbm_jump", "manual"}
PROFILE_CAPTURE_OUTCOMES = {"started", "captured", "closed_early",
                            "skipped_cooldown", "skipped_budget",
                            "skipped_inflight", "failed"}
FLIGHT_REASONS = {"crash", "hang", "health_abort", "preempt",
                  "injected_crash", "injected_crash_after_write", "manual"}
FLIGHT_OUTCOMES = {"written", "failed"}
SERVE_REQUEST_OUTCOMES = {"ok", "error", "rejected", "cancelled"}
SERVE_DRAIN_REASONS = {"close", "sigterm"}
SERVE_DRAIN_OUTCOMES = {"flushed", "timeout"}
# serve/slo.py SHED_REASONS and serve/swap.py SWAP_PHASES/SWAP_OUTCOMES
# (kept in sync by tests/test_serve_pool.py)
SERVE_SHED_REASONS = {"queue_full", "rate_limited", "draining"}
SERVE_SWAP_PHASES = {"warm", "canary", "promote", "rollback"}
SERVE_SWAP_OUTCOMES = {"started", "ok", "failed"}
LOCK_CONTENTION_KINDS = {"hold", "wait"}
# resilience/elastic.py BACKEND_LOST_KINDS (kept in sync by
# tests/test_elastic.py): the classifier's verdict on a lost backend
BACKEND_LOST_KINDS = {"connection_lost", "timeout", "version_skew",
                      "unknown"}
# data plane (data/snapshot.py + data/service.py; kept in sync by
# tests/test_data_service.py): 'restored' = the loader replays its exact
# checkpointed position, 'fresh' = the checkpoint carried no loader state
DATA_RESUME_VERDICTS = {"restored", "fresh"}
DATA_SERVICE_ROLES = {"server", "client"}
# cold path (core/excache.py EXCACHE_INVALID_REASONS, kept in sync by
# tests/test_excache.py): why a present cache entry was refused
EXCACHE_INVALID_REASONS = {"version_skew", "topology_skew", "corrupt",
                           "deserialize_failed"}
# live telemetry plane (obs/telemetry.py TELEMETRY_OUTCOMES, kept in
# sync by tests/test_telemetry.py)
TELEMETRY_SERVER_OUTCOMES = {"started", "stopped", "failed"}
# serve/transport.py TRANSPORT_OUTCOMES / TRANSPORT_SERVER_OUTCOMES
# (kept in sync by tests/test_transport.py): the front door's per-request
# verdicts and the endpoint's lifecycle
TRANSPORT_OUTCOMES = {"ok", "error", "shed", "deadline", "bad_request",
                      "torn"}
TRANSPORT_SERVER_OUTCOMES = {"started", "stopped", "failed"}
# perf attribution plane (obs/costmodel.py COLLECTIVE_KINDS, kept in
# sync by tests/test_perfwatch.py): the HLO collective opcodes the
# inventory parser recognizes
PERF_COLLECTIVE_KINDS = {"all-reduce", "all-gather", "reduce-scatter",
                         "all-to-all", "collective-permute"}
# goodput plane (obs/goodput.py GOODPUT_BUCKETS, kept in sync by
# tests/test_goodput.py): every wall-clock second of a run lands in
# exactly one of these
GOODPUT_BUCKETS = {"productive_step", "data_wait", "compile", "checkpoint",
                   "host_loss_recovery", "replica_respawn",
                   "rendezvous_wait", "drain", "overhead"}
# burn-rate alerting (obs/alerts.py ALERT_SEVERITIES, kept in sync by
# tests/test_alerts.py)
ALERT_SEVERITIES = {"page", "ticket"}
# cross-process trace context (obs/propagate.py): W3C-traceparent-shaped
# ids stamped onto journal events written under an installed context —
# any event may carry them, so the hex-shape check applies everywhere
TRACE_ID_RE = re.compile(r"^[0-9a-f]{32}$")
SPAN_ID_RE = re.compile(r"^[0-9a-f]{16}$")


def check_journal(path: str, require_exit: bool = False,
                  strict: bool = False) -> List[str]:
    """Returns a list of violations ('' prefix stripped); empty = valid.

    strict: unknown event types become violations (default: tolerated for
    forward compatibility) and a clean terminal `exit` event is required.
    """
    require_exit = require_exit or strict
    errors: List[str] = []
    events: List[dict] = []
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError as e:
        return [f"{path}: unreadable: {e}"]
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            # only the FINAL line may be torn (crash mid-write); anywhere
            # else it is corruption
            if i == len(lines):
                errors.append(f"{path}:{i}: torn final line (tolerated by "
                              "readers, but the run died mid-write)")
            else:
                errors.append(f"{path}:{i}: unparseable JSON")
            continue
        if not isinstance(row, dict):
            errors.append(f"{path}:{i}: not a JSON object")
            continue
        for k in ENVELOPE:
            if k not in row:
                errors.append(f"{path}:{i}: missing envelope field {k!r}")
        ev = row.get("event")
        if ev not in EVENT_FIELDS:
            if strict:
                errors.append(f"{path}:{i}: unknown event type {ev!r}")
            events.append(row)
            continue
        for k in EVENT_FIELDS[ev]:
            if k not in row:
                errors.append(f"{path}:{i}: {ev} event missing field {k!r}")
        if ev == "health":
            if row.get("kind") not in HEALTH_KINDS:
                errors.append(f"{path}:{i}: unknown health kind "
                              f"{row.get('kind')!r}")
            if row.get("kind") == "hang" and not row.get("stacks"):
                errors.append(f"{path}:{i}: hang event carries no thread "
                              "stacks")
        if ev == "retry" and row.get("outcome") not in RETRY_OUTCOMES:
            errors.append(f"{path}:{i}: unknown retry outcome "
                          f"{row.get('outcome')!r}")
        if ev == "profile_capture":
            if row.get("reason") not in PROFILE_CAPTURE_REASONS:
                errors.append(f"{path}:{i}: unknown profile_capture reason "
                              f"{row.get('reason')!r}")
            if row.get("outcome") not in PROFILE_CAPTURE_OUTCOMES:
                errors.append(f"{path}:{i}: unknown profile_capture outcome "
                              f"{row.get('outcome')!r}")
        if ev == "flight_dump":
            if row.get("reason") not in FLIGHT_REASONS:
                errors.append(f"{path}:{i}: unknown flight_dump reason "
                              f"{row.get('reason')!r}")
            if row.get("outcome") not in FLIGHT_OUTCOMES:
                errors.append(f"{path}:{i}: unknown flight_dump outcome "
                              f"{row.get('outcome')!r}")
        if ev == "serve_request" and \
                row.get("outcome") not in SERVE_REQUEST_OUTCOMES:
            errors.append(f"{path}:{i}: unknown serve_request outcome "
                          f"{row.get('outcome')!r}")
        if ev == "serve_batch":
            bucket, size = row.get("bucket"), row.get("size")
            if not isinstance(bucket, int) or not isinstance(size, int):
                errors.append(f"{path}:{i}: serve_batch bucket/size must "
                              f"be ints, got {bucket!r}/{size!r}")
            elif not 1 <= size <= bucket:
                errors.append(f"{path}:{i}: serve_batch size {size} "
                              f"outside [1, bucket={bucket}] — padding "
                              "arithmetic is broken")
        if ev == "serve_drain":
            if row.get("reason") not in SERVE_DRAIN_REASONS:
                errors.append(f"{path}:{i}: unknown serve_drain reason "
                              f"{row.get('reason')!r}")
            if row.get("outcome") not in SERVE_DRAIN_OUTCOMES:
                errors.append(f"{path}:{i}: unknown serve_drain outcome "
                              f"{row.get('outcome')!r}")
        if ev == "serve_shed" and row.get("reason") not in SERVE_SHED_REASONS:
            errors.append(f"{path}:{i}: unknown serve_shed reason "
                          f"{row.get('reason')!r}")
        if ev == "serve_swap":
            if row.get("phase") not in SERVE_SWAP_PHASES:
                errors.append(f"{path}:{i}: unknown serve_swap phase "
                              f"{row.get('phase')!r}")
            if row.get("outcome") not in SERVE_SWAP_OUTCOMES:
                errors.append(f"{path}:{i}: unknown serve_swap outcome "
                              f"{row.get('outcome')!r}")
        if ev in ("replica_lost", "replica_recovered"):
            if not isinstance(row.get("replica"), str) or not row.get("replica"):
                errors.append(f"{path}:{i}: {ev} replica must be a replica "
                              f"id, got {row.get('replica')!r}")
            if not isinstance(row.get("attempt"), int):
                errors.append(f"{path}:{i}: {ev} attempt must be an int, "
                              f"got {row.get('attempt')!r}")
        if ev == "lock_contention":
            if row.get("kind") not in LOCK_CONTENTION_KINDS:
                errors.append(f"{path}:{i}: unknown lock_contention kind "
                              f"{row.get('kind')!r}")
            if not isinstance(row.get("ms"), (int, float)):
                errors.append(f"{path}:{i}: lock_contention ms must be "
                              f"numeric, got {row.get('ms')!r}")
        if ev == "lock_order_violation":
            for k in ("lock_a", "lock_b"):
                if not isinstance(row.get(k), str) or not row.get(k):
                    errors.append(f"{path}:{i}: lock_order_violation {k} "
                                  f"must be a lock name, got "
                                  f"{row.get(k)!r}")
        if ev == "data_resume":
            if row.get("verdict") not in DATA_RESUME_VERDICTS:
                errors.append(f"{path}:{i}: unknown data_resume verdict "
                              f"{row.get('verdict')!r}")
            for k in ("epoch", "batches"):
                if not isinstance(row.get(k), int):
                    errors.append(f"{path}:{i}: data_resume {k} must be "
                                  f"an int, got {row.get(k)!r}")
        if ev in ("data_worker_lost", "data_worker_recovered"):
            for k in ("worker", "attempt"):
                if not isinstance(row.get(k), int):
                    errors.append(f"{path}:{i}: {ev} {k} must be an int, "
                                  f"got {row.get(k)!r}")
        if ev == "data_service":
            if row.get("role") not in DATA_SERVICE_ROLES:
                errors.append(f"{path}:{i}: unknown data_service role "
                              f"{row.get('role')!r}")
            if not isinstance(row.get("batches"), int):
                errors.append(f"{path}:{i}: data_service batches must be "
                              f"an int, got {row.get('batches')!r}")
        if ev in ("excache_hit", "excache_miss", "excache_store",
                  "excache_invalid"):
            if not isinstance(row.get("key"), str) or not row.get("key"):
                errors.append(f"{path}:{i}: {ev} key must be a cache key "
                              f"string, got {row.get('key')!r}")
            if ev == "excache_invalid" and \
                    row.get("reason") not in EXCACHE_INVALID_REASONS:
                errors.append(f"{path}:{i}: unknown excache_invalid reason "
                              f"{row.get('reason')!r}")
        if ev == "telemetry_server":
            if row.get("outcome") not in TELEMETRY_SERVER_OUTCOMES:
                errors.append(f"{path}:{i}: unknown telemetry_server "
                              f"outcome {row.get('outcome')!r}")
            if not isinstance(row.get("port"), int):
                errors.append(f"{path}:{i}: telemetry_server port must be "
                              f"an int, got {row.get('port')!r}")
        if ev == "transport_request":
            if row.get("outcome") not in TRANSPORT_OUTCOMES:
                errors.append(f"{path}:{i}: unknown transport_request "
                              f"outcome {row.get('outcome')!r}")
            # status 0 = no response ever hit the wire (a torn frame
            # closes the connection instead of answering)
            if not isinstance(row.get("status"), int) \
                    or row.get("status", -1) < 0:
                errors.append(f"{path}:{i}: transport_request status must "
                              f"be a non-negative int HTTP status, got "
                              f"{row.get('status')!r}")
            if not isinstance(row.get("deadline_ms"), (int, float)) \
                    or row.get("deadline_ms", -1) < 0:
                errors.append(f"{path}:{i}: transport_request deadline_ms "
                              f"must be non-negative (0 = none), got "
                              f"{row.get('deadline_ms')!r}")
        if ev == "transport_server":
            if row.get("outcome") not in TRANSPORT_SERVER_OUTCOMES:
                errors.append(f"{path}:{i}: unknown transport_server "
                              f"outcome {row.get('outcome')!r}")
            if not isinstance(row.get("port"), int):
                errors.append(f"{path}:{i}: transport_server port must be "
                              f"an int, got {row.get('port')!r}")
        if ev == "perf_profile":
            # compiled-artifact introspection (obs/perfwatch.py): name is
            # the jit pair, the collective roll-up must be consistent
            # (flops/bytes_accessed may be None where the backend hides
            # its cost analysis — absence of data, not a violation)
            if not isinstance(row.get("name"), str) or not row.get("name"):
                errors.append(f"{path}:{i}: perf_profile name must be a "
                              f"jit-pair name, got {row.get('name')!r}")
            for k in ("collective_count", "collective_bytes"):
                if not isinstance(row.get(k), int) or row.get(k, -1) < 0:
                    errors.append(f"{path}:{i}: perf_profile {k} must be "
                                  f"a non-negative int, got {row.get(k)!r}")
            for k in ("flops", "bytes_accessed"):
                if row.get(k) is not None and \
                        not isinstance(row.get(k), (int, float)):
                    errors.append(f"{path}:{i}: perf_profile {k} must be "
                                  f"numeric or null, got {row.get(k)!r}")
        if ev == "perf_collective":
            if row.get("kind") not in PERF_COLLECTIVE_KINDS:
                errors.append(f"{path}:{i}: unknown perf_collective kind "
                              f"{row.get('kind')!r}")
            if not isinstance(row.get("ops"), int) or row.get("ops", 0) < 1:
                errors.append(f"{path}:{i}: perf_collective ops must be a "
                              f"positive int, got {row.get('ops')!r}")
            if not isinstance(row.get("bytes"), int) or \
                    row.get("bytes", 0) <= 0:
                errors.append(f"{path}:{i}: perf_collective bytes must be "
                              f"positive, got {row.get('bytes')!r}")
        if ev == "perf_regression":
            # the gate's breach record (tools/perf_gate.py): all three
            # numbers must be present and numeric — a regression event
            # that can't say what it compared is not evidence
            if not isinstance(row.get("metric"), str) or \
                    not row.get("metric"):
                errors.append(f"{path}:{i}: perf_regression metric must "
                              f"be a metric name, got {row.get('metric')!r}")
            for k in ("baseline", "observed", "threshold"):
                if not isinstance(row.get(k), (int, float)):
                    errors.append(f"{path}:{i}: perf_regression {k} must "
                                  f"be numeric, got {row.get(k)!r}")
        if ev in ("goodput_interval", "goodput_summary"):
            # wall-clock attribution (obs/goodput.py): buckets is a
            # {bucket: seconds} mapping over the closed enum — a key
            # this checker has never heard of means the producer and
            # the offline tooling disagree about where time can go
            b = row.get("buckets")
            if not isinstance(b, dict) or not all(
                    k in GOODPUT_BUCKETS and
                    isinstance(v, (int, float)) and not isinstance(v, bool)
                    and v >= 0 for k, v in b.items()):
                errors.append(f"{path}:{i}: {ev} buckets must map known "
                              f"bucket names to non-negative seconds, got "
                              f"{b!r}")
            dur_key = "dur_s" if ev == "goodput_interval" else "wall_s"
            d = row.get(dur_key)
            if not isinstance(d, (int, float)) or isinstance(d, bool) \
                    or d < 0:
                errors.append(f"{path}:{i}: {ev} {dur_key} must be "
                              f"non-negative seconds, got {d!r}")
        if ev == "goodput_summary":
            for k in ("goodput_frac", "imbalance_frac"):
                v = row.get(k)
                if not isinstance(v, (int, float)) or isinstance(v, bool) \
                        or not 0.0 <= v <= 1.0:
                    errors.append(f"{path}:{i}: goodput_summary {k} must "
                                  f"be a fraction in [0, 1], got {v!r}")
        if ev in ("alert_fired", "alert_resolved"):
            if not isinstance(row.get("rule"), str) or not row.get("rule"):
                errors.append(f"{path}:{i}: {ev} rule must be a rule name, "
                              f"got {row.get('rule')!r}")
            if row.get("severity") not in ALERT_SEVERITIES:
                errors.append(f"{path}:{i}: unknown {ev} severity "
                              f"{row.get('severity')!r}")
        if ev == "alert_fired":
            for k in ("value", "threshold"):
                if not isinstance(row.get(k), (int, float)) or \
                        isinstance(row.get(k), bool):
                    errors.append(f"{path}:{i}: alert_fired {k} must be "
                                  f"numeric, got {row.get(k)!r}")
        if ev == "alert_resolved" and (
                not isinstance(row.get("dur_s"), (int, float))
                or isinstance(row.get("dur_s"), bool)
                or row.get("dur_s", -1) < 0):
            errors.append(f"{path}:{i}: alert_resolved dur_s must be "
                          f"non-negative seconds, got {row.get('dur_s')!r}")
        # trace context rides ANY event written under an installed
        # context (obs/journal.py stamps it); when present the ids must
        # be W3C-shaped or obs/merge.py's timelines silently fragment
        if "trace_id" in row or "span_id" in row:
            tid, sid = row.get("trace_id"), row.get("span_id")
            if not (isinstance(tid, str) and TRACE_ID_RE.match(tid)):
                errors.append(f"{path}:{i}: trace_id must be 32 lowercase "
                              f"hex chars, got {tid!r}")
            if not (isinstance(sid, str) and SPAN_ID_RE.match(sid)):
                errors.append(f"{path}:{i}: span_id must be 16 lowercase "
                              f"hex chars, got {sid!r}")
            psid = row.get("parent_span_id")
            if psid is not None and not (isinstance(psid, str)
                                         and SPAN_ID_RE.match(psid)):
                errors.append(f"{path}:{i}: parent_span_id must be 16 "
                              f"lowercase hex chars, got {psid!r}")
        if ev == "quant_calibrated":
            if not isinstance(row.get("accepted"), bool):
                errors.append(f"{path}:{i}: quant_calibrated accepted must "
                              f"be a bool, got {row.get('accepted')!r}")
            if not isinstance(row.get("delta"), (int, float)):
                errors.append(f"{path}:{i}: quant_calibrated delta must be "
                              f"numeric, got {row.get('delta')!r}")
        if ev == "sharding_resolved":
            # declarative sharding resolution (parallel/shardmap.py):
            # model names the rules table, the three counts are the
            # coverage ledger, mesh is the {axis: size} it resolved on
            if not isinstance(row.get("model"), str) or not row.get("model"):
                errors.append(f"{path}:{i}: sharding_resolved model must "
                              f"be a table name, got {row.get('model')!r}")
            for k in ("matched", "unmatched", "sharded_leaves"):
                if not isinstance(row.get(k), int):
                    errors.append(f"{path}:{i}: sharding_resolved {k} "
                                  f"must be an int, got {row.get(k)!r}")
            m = row.get("mesh")
            if not isinstance(m, dict) or not m or not all(
                    isinstance(k, str) and isinstance(v, int)
                    for k, v in m.items()):
                errors.append(f"{path}:{i}: sharding_resolved mesh must "
                              "be a non-empty {axis: size} mapping, got "
                              f"{m!r}")
        if ev in ("host_lost", "host_joined"):
            # elastic membership events (resilience/rendezvous.py):
            # host is a member ID string, generation the rendezvous
            # generation the event happened at
            if not isinstance(row.get("host"), str) or not row.get("host"):
                errors.append(f"{path}:{i}: {ev} host must be a member id "
                              f"string, got {row.get('host')!r}")
            if not isinstance(row.get("generation"), int):
                errors.append(f"{path}:{i}: {ev} generation must be an "
                              f"int, got {row.get('generation')!r}")
        if ev == "world_resized":
            for k in ("from", "to", "generation", "resume_step"):
                if not isinstance(row.get(k), int):
                    errors.append(f"{path}:{i}: world_resized {k} must be "
                                  f"an int, got {row.get(k)!r}")
            frm, to = row.get("from"), row.get("to")
            # same-SIZE resizes are legal (one host lost + one joined in
            # the same generation); an empty new world is not
            if isinstance(to, int) and to < 1:
                errors.append(f"{path}:{i}: world_resized {frm} -> {to}: "
                              "the new world must have >= 1 host")
        if ev == "data_reshard":
            for k in ("generation", "from", "to"):
                if not isinstance(row.get(k), int):
                    errors.append(f"{path}:{i}: data_reshard {k} must be "
                                  f"an int, got {row.get(k)!r}")
        if ev == "backend_lost" and row.get("kind") not in BACKEND_LOST_KINDS:
            errors.append(f"{path}:{i}: unknown backend_lost kind "
                          f"{row.get('kind')!r}")
        if ev == "backend_recovered" and \
                not isinstance(row.get("attempt"), int):
            errors.append(f"{path}:{i}: backend_recovered attempt must be "
                          f"an int, got {row.get('attempt')!r}")
        if ev == "preempt_checkpoint":
            if not isinstance(row.get("saved"), bool):
                errors.append(f"{path}:{i}: preempt_checkpoint saved must "
                              f"be a bool, got {row.get('saved')!r}")
            if not isinstance(row.get("step"), int):
                errors.append(f"{path}:{i}: preempt_checkpoint step must "
                              f"be an int, got {row.get('step')!r}")
        if ev == "straggler":
            if not isinstance(row.get("host"), int):
                errors.append(f"{path}:{i}: straggler host must be a "
                              "process index (int), got "
                              f"{row.get('host')!r}")
            if not isinstance(row.get("gap_ms"), (int, float)):
                errors.append(f"{path}:{i}: straggler gap_ms must be "
                              f"numeric, got {row.get('gap_ms')!r}")
        events.append(row)
    if not events:
        errors.append(f"{path}: no events")
        return errors
    terminal = [e for e in events if e.get("event") in ("exit", "crash")]
    if require_exit:
        if not terminal:
            errors.append(f"{path}: no terminal event (run still alive or "
                          "SIGKILLed)")
        elif terminal[-1]["event"] != "exit":
            errors.append(f"{path}: terminal event is a crash marker: "
                          f"{terminal[-1].get('reason')!r}")
    return errors


def check_trace(path: str) -> List[str]:
    """Validate Trace Event Format structure; empty list = valid."""
    errors: List[str] = []
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{path}: not valid JSON: {e}"]
    if isinstance(doc, dict):
        events = doc.get("traceEvents")
        if not isinstance(events, list):
            return [f"{path}: object form must carry a traceEvents list"]
    elif isinstance(doc, list):
        events = doc
    else:
        return [f"{path}: trace must be a JSON array or object"]
    n_complete = 0
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            errors.append(f"{path}: event[{i}] is not an object")
            continue
        if "name" not in e or "ph" not in e:
            errors.append(f"{path}: event[{i}] missing name/ph")
            continue
        if e["ph"] == "X":
            n_complete += 1
            for k in ("ts", "dur", "pid", "tid"):
                if k not in e:
                    errors.append(
                        f"{path}: complete event[{i}] "
                        f"({e['name']!r}) missing {k!r}")
            if e.get("dur", 0) < 0:
                errors.append(f"{path}: event[{i}] negative duration")
    if n_complete == 0:
        errors.append(f"{path}: no complete ('X') span events")
    return errors


def main(argv=None) -> int:
    p = UsageErrorParser(description=__doc__.splitlines()[0])
    p.add_argument("journals", nargs="+", help="journal JSONL path(s)")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="also validate this Chrome trace JSON")
    p.add_argument("--require-exit", action="store_true",
                   help="fail unless the journal ends in a clean exit "
                        "event (the obs-smoke gate)")
    p.add_argument("--strict", action="store_true",
                   help="unknown event types are violations too, and a "
                        "clean exit marker is required")
    args = p.parse_args(argv)

    errors: List[str] = []
    for path in args.journals:
        errs = check_journal(path, require_exit=args.require_exit,
                             strict=args.strict)
        errors += errs
        if not errs:
            from deep_vision_tpu.obs.journal import read_journal

            counts: dict = {}
            for e in read_journal(path):
                counts[e["event"]] = counts.get(e["event"], 0) + 1
            print(f"OK {path}: " + " ".join(
                f"{k}x{n}" for k, n in sorted(counts.items())))
    if args.trace:
        errs = check_trace(args.trace)
        errors += errs
        if not errs:
            with open(args.trace) as f:
                doc = json.load(f)
            events = doc["traceEvents"] if isinstance(doc, dict) else doc
            names = sorted({e["name"] for e in events if e.get("ph") == "X"})
            print(f"OK {args.trace}: {len(events)} events, "
                  f"spans: {', '.join(names)}")
    for e in errors:
        print("FAIL " + e, file=sys.stderr)
    return EXIT_INVALID if errors else EXIT_OK


if __name__ == "__main__":
    raise SystemExit(main())
