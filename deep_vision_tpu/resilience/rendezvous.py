"""Elastic multi-host rendezvous: survive host churn, not just device loss.

PR 10 made a single process preemption-native; this module is its
multi-HOST half (ROADMAP item 1's declared leftover). Today a dead host
makes every `multihost.sync_hosts` / `agree_flag` collective hang until
a watchdog dumps stacks — the run dies by timeout, not by policy. The
real fleet failures in the repo's own history are host-MEMBERSHIP
events: MULTICHIP_r01 was a version-skewed host admitted into the world
(fatal 4 minutes in), r04/r05 were dead tunnels every surviving host
then hung on. The standard answer (torchelastic-style generation-
numbered rendezvous) is a coordinator that treats an N→M world-size
change as an *expected input*:

- membership is a set of leases: every host heartbeats a member record;
  a missed heartbeat past the lease deadline IS the `host_lost` signal,
  typed and bounded, never an indefinite collective hang;
- the world is versioned by a **generation** number: host death (or a
  new host joining) moves the survivors to generation g+1 with a fresh
  dense rank assignment and a fresh jax coordinator address;
- every barrier/agree is deadline-bounded and lease-checked, so a dead
  peer yields `HostLostError` within the heartbeat deadline;
- joiners exchange client/platform versions through the coordinator at
  join time: a skewed host (the MULTICHIP_r01 failure) is refused in
  seconds with kind `version_skew`, never admitted into a generation.

The backing store is a directory on a shared filesystem (the same
GCS/NFS run-dir assumption `obs/merge.py` already makes for multi-host
journals) — file-backed so it runs on CPU in tests and needs no extra
service. Records are written atomically (tmp+rename; generation records
with O_EXCL so exactly one leader wins a generation).

Why re-exec instead of in-process re-init (`HostSupervisor.reexec`):
a rank whose peer SIGKILLed mid-collective is *wedged in C++* — the
gloo/ICI op never returns, `jax.distributed.shutdown()` blocks on a
shutdown barrier the dead host can never join, and the coordination-
service client terminates the whole process when it polls the peer's
death (xla client.h:80 — measured, not theorized). torchelastic reaches
the same verdict: you cannot rescue a rank from a dead collective; you
restart it. Here the *host agent keeps its process slot*: detection and
the g+1 rendezvous happen in-process (seconds, deadline-bounded), the
typed events are journaled, and then the survivor replaces its own
process image (`os.execv`) into the new generation — same PID, same
journal file (append mode), fresh jax world — and resumes from the
last checkpoint via the PR 10 cross-mesh restore.

jax-free at import (the resilience/ contract): the member/heartbeat/
barrier machinery is pure stdlib, so a re-exec'd host can re-arm its
lease *before* paying the jax import.
"""
from __future__ import annotations

import dataclasses
import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from deep_vision_tpu.core import knobs

#: journal event kinds this layer emits (tools/check_journal.py --strict
#: enforces the schemas; obs/README.md documents them)
EVENT_HOST_LOST = "host_lost"
EVENT_HOST_JOINED = "host_joined"
EVENT_WORLD_RESIZED = "world_resized"
EVENT_DATA_RESHARD = "data_reshard"

#: refusal kinds carried by RendezvousRefused (preflight reports them)
REFUSAL_VERSION_SKEW = "version_skew"
REFUSAL_EVICTED = "evicted"

#: env var a re-exec'd host agent reads to know which generation to
#: attach to instead of joining from scratch
ENV_GENERATION = "DVT_RDZV_GENERATION"


class RendezvousError(RuntimeError):
    """Base for rendezvous-layer failures."""


class HostLostError(RendezvousError):
    """A member's lease expired (or a collective deadline passed): the
    typed form of what used to be an indefinite hang. `host` is the dead
    member's id (None when only the deadline fired — a peer is
    unresponsive but the lease ledger cannot name it, e.g. the raw jax
    collective fallback path)."""

    def __init__(self, host: Optional[str], generation: int,
                 detail: str = "", lease_gap_s: Optional[float] = None):
        self.host = host
        self.generation = int(generation)
        self.lease_gap_s = lease_gap_s
        msg = (f"host {host!r} lost at generation {generation}"
               if host is not None else
               f"peer unresponsive at generation {generation}")
        super().__init__(msg + (f": {detail}" if detail else ""))


class RendezvousTimeout(RendezvousError):
    """A join/resize/barrier deadline passed with every known member
    still alive — the world never assembled (wrong --expect-hosts, a
    host that never launched)."""


class RendezvousRefused(RendezvousError):
    """This host was refused admission (kind `version_skew`: its
    client/platform versions disagree with the incumbent world's —
    the MULTICHIP_r01 failure, caught at join in seconds instead of
    minutes into the first compile)."""

    def __init__(self, kind: str, detail: str = ""):
        self.kind = kind
        super().__init__(f"rendezvous refused [{kind}]"
                         + (f": {detail}" if detail else ""))


class WorldResized(RendezvousError):
    """Control-flow signal, not a failure: the world moved to a new
    generation and this process must re-enter it (tear down jax, rebuild
    the mesh, resume from checkpoint). `Trainer.fit` raises it after
    journaling `host_lost`/`world_resized`; the host agent catches it
    and calls `HostSupervisor.reexec(view)` (or rebuilds in place when
    no jax world was ever initialized)."""

    def __init__(self, view: "WorldView", resume_step: Optional[int] = None):
        self.view = view
        self.resume_step = resume_step
        super().__init__(
            f"world resized to generation {view.generation} "
            f"({view.world_size} host(s)); resume_step={resume_step}")


@dataclasses.dataclass(frozen=True)
class WorldView:
    """One generation's membership, as seen by one host.

    `hosts` is the generation record's member-id tuple IN RECORD ORDER:
    the generation leader first (rank 0 must be the host that allocated
    — and can actually bind — the coordinator address in the record),
    then the rest sorted. A host's rank is its index — dense,
    deterministic, and re-derived per generation, which is what lets
    `multihost.host_shard`/`per_host_batch_size` re-derive a
    disjoint+covering assignment after an N→M resize instead of reading
    a process_count() frozen at init time.
    """

    generation: int
    hosts: Tuple[str, ...]
    host: str
    coordinator: Optional[str] = None  # "host:port" for jax.distributed

    @property
    def world_size(self) -> int:
        return len(self.hosts)

    @property
    def rank(self) -> int:
        return self.hosts.index(self.host)

    def shard(self) -> Tuple[int, int]:
        """(shard_index, num_shards) for host-sharded input pipelines —
        the generation-aware value behind `multihost.host_shard`."""
        return self.rank, self.world_size

    def to_dict(self) -> dict:
        return {"generation": self.generation, "hosts": list(self.hosts),
                "host": self.host, "coordinator": self.coordinator}


def _atomic_write(path: str, payload: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        # mid-rename read or a torn writer: treat as absent, the poll
        # loop re-reads
        return None


def free_port(host: str = "127.0.0.1") -> int:
    """A free TCP port on `host` — the generation leader allocates the
    jax coordinator's port here (the leader IS rank 0, so the port is
    allocated on the machine that will bind it)."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def versions_compatible(mine: Dict[str, str],
                        theirs: Dict[str, str]) -> Tuple[bool, str]:
    """The join-time version handshake, as a pure function.

    Compares `client_version` (jax/jaxlib pair) and `platform_version`
    (the libtpu build string — the terminal half of the MULTICHIP_r01
    skew) field by field; a field one side did not report is not a
    mismatch (heterogeneous probes must not fail closed on missing
    introspection). Returns (ok, detail)."""
    for key in ("client_version", "platform_version"):
        a, b = mine.get(key), theirs.get(key)
        if a and b and a != b:
            return False, f"{key} skew: joiner has {a!r}, world has {b!r}"
    return True, ""


class Rendezvous:
    """File-backed, generation-numbered membership for one host.

    Layout under `root` (a shared directory):

        members/<host>.json            lease record, rewritten per heartbeat
        refused/<host>.json            admission refusals (version_skew)
        gen/<g>.json                   generation record (hosts, coordinator),
                                       O_EXCL-created by the generation leader
        barriers/<g>/<name>#<seq>/<host>.json   barrier/agree ballots

    Leadership per generation = the lexicographically lowest live,
    version-compatible member id; the version REFERENCE is the earliest
    joiner still alive (the incumbent world refuses the skewed joiner,
    not the other way around). Barrier names carry a per-name sequence
    counter so the same name may be used repeatedly (every host calls
    the same barriers in the same order — the SPMD discipline jax
    collectives already require).
    """

    def __init__(self, root: str, host: str,
                 heartbeat_s: float = 2.0, lease_s: Optional[float] = None,
                 poll_s: float = 0.05,
                 coordinator_host: str = "127.0.0.1",
                 client_version: Optional[str] = None,
                 platform_version: Optional[str] = None):
        if not host or "/" in host:
            raise ValueError(f"host id must be a non-empty path-safe "
                             f"string, got {host!r}")
        self.root = root
        self.host = host
        self.heartbeat_s = float(heartbeat_s)
        #: a member is dead when its record is older than this (3 beats
        #: by default: one lost write is jitter, three is a corpse)
        self.lease_s = float(lease_s) if lease_s is not None \
            else 3.0 * self.heartbeat_s
        self.poll_s = float(poll_s)
        self.coordinator_host = coordinator_host
        self.versions = {}
        if client_version:
            self.versions["client_version"] = str(client_version)
        if platform_version:
            self.versions["platform_version"] = str(platform_version)
        self.generation = -1  # no world yet
        self.view: Optional[WorldView] = None
        self._joined_ts = time.time()  # join() restamps at the real join
        # when a version disagreement is only a TIEBREAK loss (equal
        # compatibility scores), self-refusal waits this long for more
        # voters: a correct host polling in the instant before its peers'
        # member records land must not be poisoned by a stale
        # first-writer. A genuine 1-vs-1 skew still refuses within ~2
        # heartbeats — seconds, not the join deadline.
        self._tie_grace_s = 2.0 * self.heartbeat_s
        self._tie_since: Optional[float] = None
        self._seq: Dict[str, int] = {}
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        for sub in ("members", "refused", "gen", "barriers"):
            os.makedirs(os.path.join(root, sub), exist_ok=True)

    # -- member records ----------------------------------------------------

    def _member_path(self, host: str) -> str:
        return os.path.join(self.root, "members", f"{host}.json")

    def _write_member(self) -> None:
        _atomic_write(self._member_path(self.host), {
            "host": self.host, "pid": os.getpid(), "ts": time.time(),
            "joined_ts": self._joined_ts, **self.versions,
        })

    def members(self) -> Dict[str, dict]:
        """Every member record on disk (alive or stale)."""
        out: Dict[str, dict] = {}
        mdir = os.path.join(self.root, "members")
        for name in sorted(os.listdir(mdir)):
            if not name.endswith(".json") or name.startswith("."):
                continue
            rec = _read_json(os.path.join(mdir, name))
            if rec and rec.get("host"):
                out[str(rec["host"])] = rec
        return out

    def alive(self, now: Optional[float] = None) -> Dict[str, dict]:
        now = time.time() if now is None else now
        return {h: r for h, r in self.members().items()
                if now - float(r.get("ts", 0)) <= self.lease_s}

    def lease_gap(self, host: str) -> Optional[float]:
        rec = self.members().get(host)
        if rec is None:
            return None
        return time.time() - float(rec.get("ts", 0))

    # -- heartbeats --------------------------------------------------------

    def start_heartbeat(self) -> None:
        """Arm the lease: write the member record now (synchronously, so
        the lease exists before this call returns — a re-exec'd host
        re-arms BEFORE importing jax) and keep rewriting it from a
        daemon thread."""
        self._write_member()
        if self._hb_thread is not None and self._hb_thread.is_alive():
            return

        def beat():
            while not self._hb_stop.wait(self.heartbeat_s):
                try:
                    self._write_member()
                except OSError:
                    pass  # a shared-FS hiccup; the next beat retries

        self._hb_stop.clear()
        self._hb_thread = threading.Thread(
            target=beat, name=f"rendezvous-heartbeat-{self.host}",
            daemon=True)
        self._hb_thread.start()

    def touch(self) -> None:
        """One synchronous lease renewal (callers about to exec renew
        right before, shrinking the re-entry gap to the exec itself)."""
        self._write_member()

    def leave(self) -> None:
        """Clean departure: stop heartbeating and drop the member record
        so survivors see an empty slot, not an expiring lease."""
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2 * self.heartbeat_s)
            self._hb_thread = None
        try:
            os.remove(self._member_path(self.host))
        except OSError:
            pass

    # -- admission (the version handshake) ---------------------------------

    def _refusal_path(self, host: str) -> str:
        return os.path.join(self.root, "refused", f"{host}.json")

    @staticmethod
    def _compat_score(rec: dict, members: Dict[str, dict]) -> int:
        """How many of `members` this record's versions agree with (its
        own record included, when present) — the vote both the reference
        election and the admission tie/majority classification share."""
        return sum(1 for other in members.values()
                   if versions_compatible(rec, other)[0])

    @classmethod
    def _reference_member(cls, members: Dict[str, dict]) -> Optional[dict]:
        """The version reference: the member compatible with the MOST
        members (majority wins — a skewed host that happens to write its
        record first must not poison the whole fleet into self-refusing),
        ties broken toward the earliest joiner (the incumbent rule, which
        is all a 1-vs-1 disagreement has to go on)."""
        if not members:
            return None
        return min(members.values(),
                   key=lambda r: (-cls._compat_score(r, members),
                                  float(r.get("joined_ts", 0)),
                                  str(r.get("host"))))

    def _check_admission(self, alive: Optional[Dict[str, dict]] = None
                         ) -> None:
        """Raise RendezvousRefused if the majority world's versions
        disagree with ours, or if a still-applicable refusal marker
        stands against us. `alive`: a LIVE-members snapshot from this
        poll iteration (the join loop reads the member directory once
        per pass and shares it) — corpses must not vote: a dead fleet's
        stale records outnumbering the fresh one would otherwise elect
        a corpse as the version reference and make every healthy host
        self-refuse."""
        refusal = _read_json(self._refusal_path(self.host))
        if refusal:
            # a refusal is pinned to the VERSIONS it judged: a host the
            # operator has since upgraded to match the fleet must be
            # able to rejoin under the same id — the stale marker is
            # retired, not honored forever
            if refusal.get("versions", None) in (None, self.versions):
                raise RendezvousRefused(
                    str(refusal.get("kind", "refused")),
                    str(refusal.get("detail", "")))
            try:
                os.remove(self._refusal_path(self.host))
            except OSError:
                pass
        members = alive if alive is not None else self.alive()
        # the electorate always includes THIS host: the sweep can lag our
        # own member-record write (first poll, NFS/GCS listing delay),
        # and without our self-vote a single stale first-writer would
        # read as a strict majority and refuse us instantly — bypassing
        # the very grace window below
        electorate = dict(members)
        electorate.setdefault(self.host, {
            "host": self.host, "joined_ts": self._joined_ts,
            **self.versions})
        ref = self._reference_member(electorate)
        if ref is None or str(ref.get("host")) == self.host:
            self._tie_since = None
            return
        ok, detail = versions_compatible(self.versions, ref)
        if ok:
            self._tie_since = None
            return
        # the reference disagrees with us. A STRICT-majority reference
        # refuses immediately; a reference that won only the
        # earliest-joiner tiebreak (equal scores) gets a grace window —
        # during assembly the tie is usually transient (our compatible
        # peers' member records are milliseconds from landing), and
        # self-refusing on it would let one stale first-writer poison
        # every correct host (the majority-vote rationale, extended to
        # the race the vote itself has before all voters are visible)
        mine = electorate[self.host]
        if self._compat_score(ref, electorate) \
                <= self._compat_score(mine, electorate):
            now = time.time()
            if self._tie_since is None:
                self._tie_since = now
            if now - self._tie_since < self._tie_grace_s:
                return  # wait for more voters before condemning anyone
        # self-refusal is the fast path; also leave the marker so
        # the ledger shows WHY this host never made a generation
        _atomic_write(self._refusal_path(self.host), {
            "host": self.host, "kind": REFUSAL_VERSION_SKEW,
            "detail": detail, "versions": self.versions,
            "ts": time.time()})
        self.leave()
        raise RendezvousRefused(REFUSAL_VERSION_SKEW, detail)

    def _compatible(self, members: Dict[str, dict]) -> Dict[str, dict]:
        """Members whose versions agree with the majority reference (the
        leader forms generations from these only; a skewed member that
        skipped its self-check still never makes a world)."""
        ref = self._reference_member(members)
        if ref is None:
            return {}
        out = {}
        for h, r in members.items():
            ok, detail = versions_compatible(r, ref)
            if ok:
                out[h] = r
            elif not os.path.exists(self._refusal_path(h)):
                _atomic_write(self._refusal_path(h), {
                    "host": h, "kind": REFUSAL_VERSION_SKEW,
                    "detail": detail,
                    "versions": {k: r[k] for k in
                                 ("client_version", "platform_version")
                                 if k in r},
                    "ts": time.time()})
        return out

    # -- generation records ------------------------------------------------

    def _gen_path(self, g: int) -> str:
        return os.path.join(self.root, "gen", f"{g}.json")

    def _write_generation(self, g: int, hosts: Sequence[str]) -> bool:
        """O_EXCL create: exactly one leader wins generation `g`; a loser
        reads the winner's record. Returns True when we wrote it.

        Host order in the record IS the rank order, writer (= leader)
        first: rank 0 of a jax world must bind the coordinator address,
        and the port below is allocated on THIS machine — a
        lexicographically-lower member (a freshly-admitted joiner, say)
        must not inherit rank 0 and with it an address it cannot bind."""
        hosts = [self.host] + sorted(h for h in hosts if h != self.host)
        rec = {
            "generation": g, "hosts": hosts,
            "coordinator": f"{self.coordinator_host}:"
                           f"{free_port(self.coordinator_host)}",
            "leader": self.host, "ts": time.time(),
        }
        try:
            fd = os.open(self._gen_path(g),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        with os.fdopen(fd, "w") as f:
            json.dump(rec, f)
            f.flush()
            os.fsync(f.fileno())
        return True

    def read_generation(self, g: int) -> Optional[dict]:
        return _read_json(self._gen_path(g))

    def latest_generation(self) -> Optional[dict]:
        gdir = os.path.join(self.root, "gen")
        best = None
        for name in os.listdir(gdir):
            if name.endswith(".json"):
                try:
                    g = int(name[:-5])
                except ValueError:
                    continue
                if best is None or g > best:
                    best = g
        return self.read_generation(best) if best is not None else None

    def _adopt(self, rec: dict) -> WorldView:
        hosts = tuple(str(h) for h in rec["hosts"])  # record order IS
        # rank order (leader/coordinator-binder first)
        if self.host not in hosts:
            raise RendezvousRefused(
                REFUSAL_EVICTED,
                f"generation {rec['generation']} formed without this host "
                f"(hosts={list(hosts)}) — its lease must have lapsed")
        self.generation = int(rec["generation"])
        # this host's membership incarnation began no later than the
        # record that lists it: clamp joined_ts so a post-reexec
        # attach's member file still PREDATES the record and
        # _world_running keeps reading the world as live (a replacement
        # joiner must wait for a resize, not squat the next generation)
        rts = float(rec.get("ts", self._joined_ts))
        if rts < self._joined_ts:
            self._joined_ts = rts
            self.touch()
        # barrier sequence numbering is per generation (the dirs are):
        # members enter a generation along different histories — join,
        # in-place resize, post-exec attach — and carried-over counters
        # would split the SAME logical barrier across #k dirs
        self._seq = {}
        self.view = WorldView(generation=self.generation, hosts=hosts,
                              host=self.host,
                              coordinator=rec.get("coordinator"))
        return self.view

    # -- join / attach / resize --------------------------------------------

    def _world_running(self, rec: Optional[dict],
                       alive: Dict[str, dict]) -> bool:
        """Is the latest generation record a LIVE world (vs leftovers)?

        A member of `rec` counts as still running that world only when
        its lease is fresh AND its joined_ts predates the record (the
        same incarnation that formed it). A fleet re-joining over a
        stale directory re-stamps every joined_ts, so yesterday's
        record reads as dead and the new world forms at generation
        latest+1 — which is also how a preflight probe's leftover
        record never squats the directory the real run is about to
        claim."""
        if rec is None:
            return False
        rts = float(rec.get("ts", 0))
        for h in rec.get("hosts", ()):
            m = alive.get(str(h))
            if m is not None and float(m.get("joined_ts", rts + 1)) <= rts:
                return True
        return False

    def join(self, expect_hosts: int, timeout_s: float = 120.0) -> WorldView:
        """Enter a world of exactly `expect_hosts` version-compatible
        members. Deadline-bounded; the version handshake runs on every
        poll so a skewed joiner is refused in seconds, not at the
        deadline.

        Generations need not start at 0: a fresh fleet over a stale
        directory (a previous run's records, a preflight probe's
        leftovers) forms at latest+1. Joining while a world is RUNNING
        never overwrites it — the joiner heartbeats and waits to be
        adopted by the running world's next `resize()` (which includes
        every live compatible member: that is the host_joined/grow
        path)."""
        self._joined_ts = time.time()
        self.start_heartbeat()
        deadline = time.time() + timeout_s
        while True:
            rec = self.latest_generation()
            fresh = (rec is not None
                     and float(rec.get("ts", 0))
                     >= self._joined_ts - self.lease_s)
            if fresh and self.host in {str(h) for h in rec["hosts"]}:
                view = self._adopt(rec)
                self._ack_generation(view, deadline)
                return view
            members = self.members()  # ONE directory sweep per pass,
            now = time.time()         # shared by every sub-check below
            alive = {h: r for h, r in members.items()
                     if now - float(r.get("ts", 0)) <= self.lease_s}
            self._check_admission(alive)  # live members only: a dead
            # fleet's stale records must not out-vote the fresh ones
            compat = self._compatible(alive)
            if (len(compat) >= expect_hosts
                    and not self._world_running(rec, alive)):
                leader = sorted(compat)[0]
                if leader == self.host:
                    g = 0 if rec is None else int(rec["generation"]) + 1
                    self._write_generation(g, sorted(compat)[:expect_hosts])
                    continue  # adopt what we (or a racer) wrote
            if time.time() > deadline:
                self.leave()
                raise RendezvousTimeout(
                    f"world of {expect_hosts} never assembled within "
                    f"{timeout_s:.0f}s (alive+compatible: "
                    f"{sorted(compat)})")
            time.sleep(self.poll_s)

    def attach(self, generation: Optional[int] = None,
               timeout_s: float = 300.0) -> WorldView:
        """Re-enter an existing generation (the re-exec'd host agent's
        path: `ENV_GENERATION` names it). Re-arms the lease first, then
        blocks — deadline-bounded — on the attach barrier so every
        member of the generation is live before anyone touches
        `jax.distributed.initialize` (which would otherwise hang on a
        member still paying its jax import)."""
        self._joined_ts = getattr(self, "_joined_ts", time.time())
        self.start_heartbeat()
        if generation is None:
            generation = knobs.get_int(ENV_GENERATION)
        rec = (self.read_generation(generation) if generation is not None
               else self.latest_generation())
        if rec is None:
            raise RendezvousError(
                f"no generation record to attach to "
                f"(generation={generation!r}) under {self.root}")
        view = self._adopt(rec)
        self._ack_generation(view, time.time() + timeout_s)
        return view

    def _ack_generation(self, view: WorldView, deadline: float) -> None:
        """Everyone listed in the generation must ack before any member
        proceeds to jax init — a listed-but-dead host would otherwise
        hang the distributed handshake. Lease checks are ON: a member
        dying between the record and its ack triggers re-resize, not a
        hang. Generous deadline: an ack may be a whole process re-exec
        (python start + stdlib imports) away. seq=False: members reach
        a generation's ack along DIFFERENT call paths (join vs resize
        vs post-exec attach), so a per-name sequence counter would
        split them across barrier dirs; one fixed dir per generation is
        the meeting point. A stale pre-exec ballot can at worst let a
        member proceed to jax.distributed.initialize early, which has
        its own bounded init timeout."""
        self.barrier("gen-ack", timeout_s=max(0.0, deadline - time.time()),
                     scope=view, seq=False)

    def check(self) -> None:
        """Lease sweep over the current generation; raises HostLostError
        for the first expired member. The cheap poll the bounded device
        fences run between waits."""
        if self.view is None:
            return
        alive = self.alive()
        for h in self.view.hosts:
            if h != self.host and h not in alive:
                raise HostLostError(h, self.generation,
                                    lease_gap_s=self.lease_gap(h))

    def _resize_leader(self, survivors: List[str]) -> str:
        """Who writes the next generation: the lowest survivor that was
        IN the current generation (a waiting joiner — alive, compatible,
        but not yet a member — must not lead a world it has never been
        part of: it is busy inside join(), not resize(), and electing it
        would leave the record forever unwritten). Falls back to the
        lowest survivor when no current member survived."""
        current = set(self.view.hosts) if self.view is not None else set()
        incumbents = [h for h in survivors if h in current]
        return (incumbents or survivors)[0]

    def resize(self, max_attempts: int = 5,
               settle_s: Optional[float] = None,
               timeout_s: float = 60.0) -> WorldView:
        """Move to the next generation with every live, compatible
        member (losses shrink the world; a waiting joiner grows it).

        Convergent under churn: the new leader (lowest live member)
        creates gen g+1 with O_EXCL after a settle delay (one heartbeat,
        so a dying member's lease has a chance to lapse before the
        membership is frozen); everyone adopts the record and acks.
        If a *listed* member dies before acking, the ack barrier raises
        HostLostError and the loop tries g+2 — bounded by
        `max_attempts`."""
        settle = self.heartbeat_s if settle_s is None else settle_s
        for _ in range(max_attempts):
            g = self.generation + 1
            rec = self.read_generation(g)
            if rec is None:
                time.sleep(settle)
                survivors = sorted(self._compatible(self.alive()))
                if not survivors:
                    raise RendezvousError("no live members to resize with")
                if self._resize_leader(survivors) == self.host:
                    self._write_generation(g, survivors)
                rec = self.read_generation(g)
            if rec is None:
                # another host is the leader and has not written yet
                deadline = time.time() + timeout_s
                while rec is None and time.time() < deadline:
                    time.sleep(self.poll_s)
                    rec = self.read_generation(g)
                    if rec is None:
                        survivors = sorted(self._compatible(self.alive()))
                        if survivors and \
                                self._resize_leader(survivors) == self.host:
                            self._write_generation(g, survivors)
                if rec is None:
                    raise RendezvousTimeout(
                        f"generation {g} record never appeared "
                        f"within {timeout_s:.0f}s")
            view = self._adopt(rec)
            try:
                self._ack_generation(view, time.time() + timeout_s)
            except HostLostError:
                # a listed member died mid-resize: bump the generation
                # counter past the failed record and go again
                self.generation = int(rec["generation"])
                continue
            return view
        raise RendezvousError(
            f"membership would not settle after {max_attempts} resize "
            f"attempts (generation {self.generation})")

    # -- barriers + consensus ----------------------------------------------

    def _barrier_dir(self, name: str, scope: WorldView,
                     seq: bool = True) -> str:
        if not seq:
            return os.path.join(self.root, "barriers",
                                str(scope.generation), name)
        n = self._seq.get(name, 0)
        self._seq[name] = n + 1
        return os.path.join(self.root, "barriers",
                            str(scope.generation), f"{name}#{n}")

    def barrier(self, name: str, timeout_s: float = 60.0,
                payload: Optional[dict] = None,
                scope: Optional[WorldView] = None,
                seq: bool = True) -> Dict[str, dict]:
        """Deadline-bounded, lease-checked barrier over the generation's
        members. Returns every member's payload. Raises HostLostError
        the moment a straggler's lease expires (detection within the
        heartbeat deadline — the property the old jax-collective
        barriers could not have) and RendezvousTimeout if the deadline
        passes with everyone still alive (a logic bug — same-name
        barriers out of step — not a death)."""
        scope = scope or self.view
        if scope is None:
            raise RendezvousError("no world view: join() or attach() first")
        if scope.world_size == 1:
            return {self.host: dict(payload or {})}
        bdir = self._barrier_dir(name, scope, seq=seq)
        os.makedirs(bdir, exist_ok=True)
        _atomic_write(os.path.join(bdir, f"{self.host}.json"),
                      {"host": self.host, "ts": time.time(),
                       **(payload or {})})
        deadline = time.time() + timeout_s
        while True:
            ballots: Dict[str, dict] = {}
            for h in scope.hosts:
                rec = _read_json(os.path.join(bdir, f"{h}.json"))
                if rec is not None:
                    ballots[h] = rec
            if len(ballots) == len(scope.hosts):
                return ballots
            alive = self.alive()
            for h in scope.hosts:
                if h != self.host and h not in ballots and h not in alive:
                    # TOCTOU guard: a peer that acked AFTER our ballot
                    # sweep and then cleanly leave()d (the preflight
                    # probe's join-then-leave shape) has no lease but
                    # DID pass the barrier — re-read its ballot before
                    # declaring a corpse
                    if _read_json(os.path.join(bdir, f"{h}.json")) \
                            is not None:
                        continue  # re-sweep picks it up
                    raise HostLostError(h, scope.generation,
                                        detail=f"missed barrier {name!r}",
                                        lease_gap_s=self.lease_gap(h))
            if time.time() > deadline:
                missing = sorted(set(scope.hosts) - set(ballots))
                raise RendezvousTimeout(
                    f"barrier {name!r} deadline ({timeout_s:.0f}s) passed "
                    f"with live stragglers {missing} — barrier callsites "
                    "are out of step")
            time.sleep(self.poll_s)

    def agree(self, name: str, flag: bool, timeout_s: float = 60.0) -> bool:
        """Global OR of a per-host boolean — the preemption-consensus
        primitive, deadline-bounded. Same discipline as barrier()."""
        ballots = self.barrier(name, timeout_s=timeout_s,
                               payload={"flag": bool(flag)})
        return any(bool(b.get("flag")) for b in ballots.values())


class HostSupervisor:
    """`BackendSupervisor`'s fleet-layer sibling: rendezvous + telemetry.

    Owns the journaling/metrics/flight-breadcrumb side of membership
    events so the Trainer's control flow stays readable:

        host_lost{host, generation, lease_gap_s}
        host_joined{host, generation}
        world_resized{from, to, generation, resume_step}

    plus `rendezvous_generation` / `rendezvous_hosts` gauges and
    `rendezvous_host_lost_total` / `rendezvous_resizes_total` counters.
    `bounded_fetch` is the deadline-bounded device fence the train loop
    uses in place of a bare blocking fetch: a peer SIGKILLed
    mid-collective leaves this host's fetch wedged in C++ forever, and
    only a side-channel lease sweep can name the culprit.
    """

    def __init__(self, rendezvous: Rendezvous, journal=None, registry=None,
                 fence_poll_s: float = 0.25, resume_step_fn=None,
                 reshardable: bool = True):
        self.rdzv = rendezvous
        self.journal = journal
        self._registry = registry
        self.fence_poll_s = float(fence_poll_s)
        #: () -> Optional[int]: the step a post-resize resume will land on
        #: (the Trainer wires its CheckpointManager.latest_step here)
        self.resume_step_fn = resume_step_fn
        #: input pipeline is a pure function of the generation (host_shard-
        #: keyed streams, per-host services): a resize journals a typed
        #: `data_reshard`. The Trainer clears this when an armed snapshot
        #: loader is attached — byte-identical replay cannot survive a
        #: resize, and the loader's fingerprint refuses at restore instead.
        self.reshardable = bool(reshardable)
        #: the resume_step handle_loss journaled into world_resized —
        #: callers re-raising WorldResized read THIS instead of
        #: recomputing (a directory whose latest step changed in between
        #: would make the journal disagree with the actual resume)
        self.last_resume_step: Optional[int] = None
        # exactly-once loss handling: the membership watchdog, an in-band
        # bounded fence, and fit's confirm_loss path can all detect the
        # same death within milliseconds of each other — one resize, one
        # event trail, one re-entry
        self._claim_lock = threading.Lock()
        self._claimed = False
        self._watch_stop = threading.Event()
        self._watch: Optional[threading.Thread] = None
        # one persistent fence worker serves the train loop's serial
        # fetches (two per step — per-call thread spawn would churn
        # ~20 threads/s); a fetch wedged in a dead collective leaves it
        # busy, and the rare overlapping call falls back to a one-shot
        self._fence_lock = threading.Lock()
        self._fence_q = None
        self._fence_thread: Optional[threading.Thread] = None
        self._fence_idle = threading.Event()
        self._fence_idle.set()

    # -- telemetry plumbing ------------------------------------------------

    def _metric(self, kind: str, name: str, help: str):
        reg = self._registry
        if reg is None:
            from deep_vision_tpu.obs.registry import get_registry

            reg = get_registry()
        return getattr(reg, kind)(name, help)

    def _write(self, event: str, **fields) -> None:
        if self.journal is not None:
            try:
                self.journal.write(event, **fields)
            except Exception:
                pass
        try:
            from deep_vision_tpu.obs import flight as _flight

            _flight.note(event, **{k: v for k, v in fields.items()
                                   if isinstance(v, (str, int, float, bool))})
        except Exception:
            pass

    # -- membership events -------------------------------------------------

    def on_host_lost(self, err: HostLostError) -> None:
        try:
            self._metric("counter", "rendezvous_host_lost_total",
                         "member leases expired").inc()
        except Exception:
            pass
        row = {"host": err.host if err.host is not None else "?",
               "generation": err.generation}
        if err.lease_gap_s is not None:
            row["lease_gap_s"] = round(float(err.lease_gap_s), 3)
        self._write(EVENT_HOST_LOST, **row)

    def on_host_joined(self, host: str, generation: int) -> None:
        self._write(EVENT_HOST_JOINED, host=host, generation=int(generation))

    def resize(self, resume_step: Optional[int] = None) -> WorldView:
        """Re-rendezvous at g+1 and journal the membership delta +
        the typed `world_resized` event. Returns the new view; the
        caller decides how to re-enter it (reexec, or rebuild in place
        when no jax distributed world exists)."""
        old = self.rdzv.view
        t0 = time.monotonic()
        view = self.rdzv.resize()
        rendezvous_wait_s = time.monotonic() - t0
        old_hosts = set(old.hosts) if old is not None else set()
        for h in sorted(set(view.hosts) - old_hosts):
            if h != view.host:
                self.on_host_joined(h, view.generation)
        try:
            self._metric("counter", "rendezvous_resizes_total",
                         "generation changes survived").inc()
            self._metric("gauge", "rendezvous_generation",
                         "current rendezvous generation").set(view.generation)
            self._metric("gauge", "rendezvous_hosts",
                         "live hosts in the current generation").set(
                             view.world_size)
        except Exception:
            pass
        # rendezvous_wait_s: the goodput plane (obs/goodput.py) carves
        # exactly the re-rendezvous portion of the host_lost ->
        # world_resized gap into its rendezvous_wait bucket; the rest of
        # the recovery window stays host_loss_recovery
        self._write(
            EVENT_WORLD_RESIZED,
            **{"from": len(old_hosts) if old_hosts else 0,
               "to": view.world_size, "generation": view.generation,
               "resume_step": int(resume_step)
               if resume_step is not None else -1,
               "rendezvous_wait_s": round(rendezvous_wait_s, 3)})
        return view

    def journal_data_reshard(self, view: WorldView, from_hosts: int) -> None:
        """The input-pipeline half of a resize where PR 12's re-derivable
        sharding CAN follow the world (host_shard()-keyed streams, one
        data service per host): record the new disjoint+covering slice.
        Where it cannot (an armed snapshot loader), the restore path
        refuses with SnapshotMismatch instead — journaled by its own
        data_resume machinery."""
        idx, n = view.shard()
        self._write(EVENT_DATA_RESHARD,
                    **{"generation": view.generation,
                       "from": int(from_hosts), "to": view.world_size,
                       "shard_index": idx, "num_shards": n})

    # -- the bounded device fence ------------------------------------------

    def _fence_body(self):
        while True:
            fn, out, done = self._fence_q.get()
            try:
                out["value"] = fn()
            except BaseException as e:  # re-raised on the caller thread
                out["exc"] = e
            finally:
                done.set()
                self._fence_idle.set()

    def bounded_fetch(self, fn, deadline_s: Optional[float] = None):
        """Run a blocking device fetch off-thread; between join slices,
        sweep the lease ledger. A dead peer surfaces as HostLostError
        within the heartbeat deadline; a merely slow step keeps waiting
        (compiles are slow, deaths are named) unless `deadline_s` is
        given. The fetch runs on ONE persistent worker (the train
        loop's fetches are serial; spawning per call would churn
        threads every step). A worker left wedged in a dead collective
        stays wedged — acceptable, because the only exits from there
        are a resize-and-reexec or a crash — and any overlapping call
        meanwhile falls back to a one-shot thread."""
        out: dict = {}
        done = threading.Event()
        with self._fence_lock:
            if self._fence_q is None:
                import queue as _queue

                self._fence_q = _queue.Queue()
            if (self._fence_thread is None
                    or not self._fence_thread.is_alive()):
                self._fence_thread = threading.Thread(
                    target=self._fence_body, daemon=True,
                    name="host-fence-worker")
                self._fence_thread.start()
            if self._fence_idle.is_set():
                self._fence_idle.clear()
                self._fence_q.put((fn, out, done))
            else:
                threading.Thread(
                    target=lambda: (self._run_oneshot(fn, out, done)),
                    daemon=True, name="host-bounded-fetch").start()
        deadline = (time.time() + deadline_s) if deadline_s is not None \
            else None
        while not done.wait(self.fence_poll_s):
            self.rdzv.check()  # raises HostLostError on an expired lease
            if deadline is not None and time.time() > deadline:
                raise HostLostError(
                    None, self.rdzv.generation,
                    detail=f"device fetch exceeded {deadline_s:.0f}s with "
                           "every lease fresh")
        if "exc" in out:
            raise out["exc"]
        return out["value"]

    @staticmethod
    def _run_oneshot(fn, out, done):
        try:
            out["value"] = fn()
        except BaseException as e:
            out["exc"] = e
        finally:
            done.set()

    def confirm_loss(self, exc: Exception,
                     wait_s: Optional[float] = None) -> Optional[HostLostError]:
        """Was this exception really a peer dying? A SIGKILLed host's
        surviving peers see transport errors within milliseconds — often
        BEFORE the lease expires — so a step failure polls the ledger
        for up to one lease period before handing the exception to the
        backend-supervisor path. Returns the typed loss or None."""
        wait = self.rdzv.lease_s * 1.5 if wait_s is None else wait_s
        deadline = time.time() + wait
        while True:
            try:
                self.rdzv.check()
            except HostLostError as lost:
                return lost
            if time.time() > deadline:
                return None
            time.sleep(self.rdzv.poll_s * 4)

    # -- exactly-once loss handling ----------------------------------------

    def _claim(self) -> bool:
        with self._claim_lock:
            if self._claimed:
                return False
            self._claimed = True
            return True

    def handle_loss(self, err: HostLostError) -> WorldView:
        """The one funnel every detector feeds: journal the typed
        `host_lost`, re-rendezvous at g+1, journal `world_resized` (and
        `data_reshard` when the input pipeline re-derives), return the
        new view. A second detector arriving while the first is mid-
        resize parks forever — the winner is about to replace this
        process image, and a duplicate resize/event trail would be
        worse than a parked thread."""
        if not self._claim():
            while True:  # the winning detector's reexec ends this process
                time.sleep(1.0)
        try:
            self.on_host_lost(err)
            resume_step = None
            if self.resume_step_fn is not None:
                try:
                    resume_step = self.resume_step_fn()
                except Exception:
                    resume_step = None
            self.last_resume_step = resume_step
            old_n = (self.rdzv.view.world_size
                     if self.rdzv.view is not None else 0)
            view = self.resize(resume_step=resume_step)
        except BaseException:
            # a FAILED resize must release the claim: the next detector
            # (watchdog sweep, in-band fence) gets to retry — a held
            # claim with no winner would park every detector and
            # re-create the very indefinite hang this module removes
            with self._claim_lock:
                self._claimed = False
            raise
        if self.reshardable:
            self.journal_data_reshard(view, from_hosts=old_n)
        return view

    # -- the membership watchdog -------------------------------------------

    def arm_watchdog(self, poll_s: Optional[float] = None) -> None:
        """Detection that does not care where the main thread is: a
        daemon thread sweeps the lease ledger and, on an expired lease,
        runs the full handle_loss funnel and re-execs the process into
        the new generation.

        This is not belt-and-braces — it is the PRIMARY detector. A
        peer SIGKILLed mid-step leaves this host's next jit dispatch
        blocked in C++ *before* any Python-level fence runs (donated
        buffers chain each dispatch to the previous step's completion;
        measured via stack dumps in the host smoke), so no in-band
        check can be guaranteed to execute again. The watchdog needs
        only the GIL, which C++ blocks release. The in-band paths
        (bounded fences, rendezvous barriers) still exist because when
        the main thread IS healthy they hand fit a clean typed
        WorldResized instead of an exec mid-epoch."""
        if self._watch is not None and self._watch.is_alive():
            return
        poll = self.fence_poll_s if poll_s is None else float(poll_s)
        self._watch_stop.clear()

        def body():
            while not self._watch_stop.wait(poll):
                try:
                    self.rdzv.check()
                except HostLostError as e:
                    try:
                        view = self.handle_loss(e)
                    except Exception:
                        continue  # resize failed and the claim was
                        # released: keep sweeping — the next pass (or an
                        # in-band detector) retries, so a transient
                        # resize failure never strands the run
                    self.reexec(view)

        self._watch = threading.Thread(target=body, daemon=True,
                                       name="rendezvous-watchdog")
        self._watch.start()

    def disarm_watchdog(self) -> None:
        """Stop the watchdog (clean shutdown: a completing run must not
        be exec'd out from under its own teardown)."""
        self._watch_stop.set()
        if self._watch is not None:
            self._watch.join(timeout=5.0)
            self._watch = None

    # -- re-entry ----------------------------------------------------------

    def reexec(self, view: WorldView, argv: Optional[List[str]] = None):
        """Replace this process image with itself, parameterized to
        attach to `view`'s generation (see module docstring for why a
        wedged rank cannot re-init in place). Renews the lease right
        before the exec so the re-entry gap is only the exec + python
        startup; the journal (append mode, flush per line) and the
        checkpoint (already durable) carry the run across. Never
        returns."""
        self.rdzv.touch()
        env = dict(os.environ)
        env[ENV_GENERATION] = str(view.generation)
        import sys

        argv = list(argv) if argv is not None else [sys.executable] + sys.argv
        os.execve(argv[0], argv, env)
