"""End-to-end trainer tests on the 8-device virtual CPU mesh.

The integration-smoke analog of the reference's LeNet/MNIST run
(LeNet/pytorch/train.py): a tiny synthetic problem must converge.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.core.metrics import topk_accuracy
from deep_vision_tpu.losses import classification_loss_fn
from deep_vision_tpu.models import get_model
from deep_vision_tpu.train import Trainer, build_optimizer, ReduceLROnPlateau


def synthetic_mnist(n=256, seed=0):
    """Linearly-separable-ish 32x32 images: class = brightest quadrant."""
    rng = np.random.RandomState(seed)
    images = rng.rand(n, 32, 32, 1).astype(np.float32) * 0.1
    labels = rng.randint(0, 4, size=n)
    for i, l in enumerate(labels):
        r, c = divmod(l, 2)
        images[i, r * 16:(r + 1) * 16, c * 16:(c + 1) * 16, 0] += 0.9
    return images, labels


def batches(images, labels, bs):
    for i in range(0, len(images) - bs + 1, bs):
        yield {"image": images[i:i + bs], "label": labels[i:i + bs]}


@pytest.fixture(scope="module")
def lenet_trainer(mesh8):
    model = get_model("lenet5", num_classes=4)
    tx = build_optimizer("adam", 1e-3)
    return Trainer(
        model, tx, classification_loss_fn,
        sample_input=jnp.zeros((8, 32, 32, 1)),
        mesh=mesh8,
    )


def test_train_step_decreases_loss(lenet_trainer):
    images, labels = synthetic_mnist()
    first_loss, last_loss = None, None
    for epoch in range(3):
        for batch in batches(images, labels, 32):
            metrics = lenet_trainer.train_step(batch)
            if first_loss is None:
                first_loss = float(metrics["loss"])
            last_loss = float(metrics["loss"])
    assert last_loss < first_loss * 0.5, (first_loss, last_loss)


def test_eval_accuracy_high_after_training(lenet_trainer):
    # runs after the training test (module-scoped fixture keeps state)
    images, labels = synthetic_mnist(seed=1)
    metrics = lenet_trainer.eval_step({"image": images[:64], "label": labels[:64]})
    assert float(metrics["top1"]) > 0.9


def test_state_is_replicated_on_mesh(lenet_trainer, mesh8):
    leaf = jax.tree_util.tree_leaves(lenet_trainer.state.params)[0]
    assert len(leaf.sharding.device_set) == 8


def test_topk_accuracy_exact():
    logits = jnp.array([[0.1, 0.5, 0.2, 0.0], [0.9, 0.0, 0.05, 0.05]])
    labels = jnp.array([1, 2])
    acc = topk_accuracy(logits, labels, ks=(1, 2, 3))
    assert float(acc["top1"]) == pytest.approx(0.5)
    assert float(acc["top3"]) == pytest.approx(1.0)


def test_plateau_schedule():
    from deep_vision_tpu.train.optimizers import ReduceLROnPlateau

    p = ReduceLROnPlateau(factor=0.1, patience=1, mode="max")
    assert p.step(0.5) == 1.0
    assert p.step(0.4) == 1.0   # 1 bad epoch <= patience
    assert p.step(0.4) == 0.1   # 2nd bad epoch triggers decay
    assert p.step(0.6) == 0.1   # improvement holds the new scale
    sd = p.state_dict()
    q = ReduceLROnPlateau(factor=0.1, patience=1, mode="max")
    q.load_state_dict(sd)
    assert q.scale == 0.1


def test_partial_batch_padded_and_masked(lenet_trainer):
    # 20 rows on an 8-device mesh: not divisible -> padded to 24 + masked
    images, labels = synthetic_mnist(seed=2)
    full = lenet_trainer.eval_step({"image": images[:64], "label": labels[:64]})
    part = lenet_trainer.eval_step({"image": images[:20], "label": labels[:20]})
    assert 0.0 <= float(part["top1"]) <= 1.0
    # padded rows must not dilute accuracy: a perfectly-trained model stays 1.0
    assert float(full["top1"]) == pytest.approx(1.0)
    assert float(part["top1"]) == pytest.approx(1.0)


@pytest.mark.slow
def test_fit_with_plateau_and_eval(mesh8, tmp_path):
    model = get_model("lenet5", num_classes=4)
    tx = build_optimizer("sgd", 0.05, momentum=0.9)
    trainer = Trainer(
        model, tx, classification_loss_fn,
        sample_input=jnp.zeros((8, 32, 32, 1)),
        mesh=mesh8,
        plateau=ReduceLROnPlateau(patience=0, mode="max"),
    )
    images, labels = synthetic_mnist(n=128)

    trainer.fit(
        lambda: batches(images, labels, 32),
        lambda: batches(images, labels, 32),
        epochs=2,
        eval_first=True,
    )
    assert int(trainer.state.step) == 8
    assert len(trainer.eval_logger.history["top1"]) == 3  # eval_first + 2 epochs


@pytest.mark.slow
def test_fit_raises_on_diverged_loss(mesh8):
    """Failure detection: a NaN epoch must stop the run loudly (SURVEY §5)."""
    import jax.numpy as jnp

    model = get_model("lenet5", num_classes=4)
    tx = build_optimizer("sgd", 1e-3)
    trainer = Trainer(
        model, tx, classification_loss_fn,
        sample_input=jnp.zeros((8, 32, 32, 1)), mesh=mesh8,
    )
    images, labels = synthetic_mnist(64)
    images[0] = np.nan  # a poisoned batch: the loss goes non-finite
    with pytest.raises(FloatingPointError, match="diverged"):
        trainer.fit(lambda: batches(images, labels, 32), epochs=3)


@pytest.mark.slow
def test_checkify_mode_locates_nan_in_step(mesh8):
    """Sanitizer mode (SURVEY §2.7): checkify raises a located error on the
    first poisoned op inside the jitted step, instead of finishing the epoch
    with garbage."""
    from jax.experimental import checkify as _checkify

    model = get_model("lenet5", num_classes=4)
    tx = build_optimizer("sgd", 1e-3)
    trainer = Trainer(
        model, tx, classification_loss_fn,
        sample_input=jnp.zeros((8, 32, 32, 1)), mesh=mesh8,
        checkify_errors=True,
    )
    images, labels = synthetic_mnist(64)
    # clean step passes and trains
    m = trainer.train_step({"image": images[:32], "label": labels[:32]})
    assert np.isfinite(float(m["loss"]))
    # poisoned batch raises from inside the step with a location
    bad = images[:32].copy()
    bad[0] = np.nan
    with pytest.raises(_checkify.JaxRuntimeError, match="nan"):
        trainer.train_step({"image": bad, "label": labels[:32]})


@pytest.mark.slow
def test_preemption_checkpoints_and_resumes(mesh8, tmp_path):
    """Elastic recovery (SURVEY §2.7 upstream: 'recovery = manual resume'):
    SIGTERM mid-epoch finishes the in-flight step, writes a checkpoint, and
    fit returns; a fresh Trainer resumes the incomplete epoch."""
    import os
    import signal

    from deep_vision_tpu.core import CheckpointManager

    images, labels = synthetic_mnist()

    def make():
        return Trainer(
            get_model("lenet5", num_classes=4),
            build_optimizer("adam", 1e-3),
            classification_loss_fn,
            sample_input=jnp.zeros((8, 32, 32, 1)),
            mesh=mesh8,
            checkpoint_manager=CheckpointManager(str(tmp_path)),
        )

    def preempting_batches():
        for i, b in enumerate(batches(images, labels, 32)):
            if i == 2:  # "maintenance event" after 2 steps of epoch 0
                os.kill(os.getpid(), signal.SIGTERM)
            yield b

    trainer = make()
    trainer.fit(preempting_batches, epochs=5)  # returns instead of dying
    saved_step = int(trainer.state.step)
    assert saved_step == 3  # the in-flight 3rd step completed, then stopped

    trainer2 = make()
    next_epoch = trainer2.resume()
    assert next_epoch == 0  # incomplete epoch is re-run
    assert int(trainer2.state.step) == saved_step
    trainer2.fit(lambda: batches(images, labels, 32), epochs=2,
                 start_epoch=next_epoch)
    assert int(trainer2.state.step) == saved_step + 2 * 8


@pytest.mark.slow
def test_preemption_during_eval_saves_completed_epoch(mesh8, tmp_path):
    """SIGTERM mid-eval: eval bails early, the finished training epoch is
    checkpointed as complete, and resume continues at the NEXT epoch."""
    import os
    import signal

    from deep_vision_tpu.core import CheckpointManager

    images, labels = synthetic_mnist()

    def make():
        return Trainer(
            get_model("lenet5", num_classes=4),
            build_optimizer("adam", 1e-3),
            classification_loss_fn,
            sample_input=jnp.zeros((8, 32, 32, 1)),
            mesh=mesh8,
            checkpoint_manager=CheckpointManager(str(tmp_path)),
        )

    def preempting_eval():
        os.kill(os.getpid(), signal.SIGTERM)
        yield from batches(images[:64], labels[:64], 32)

    trainer = make()
    trainer.fit(lambda: batches(images, labels, 32), preempting_eval,
                epochs=5)
    assert int(trainer.state.step) == 8  # epoch 0 trained fully

    trainer2 = make()
    assert trainer2.resume() == 1  # epoch 0 is complete; eval is re-runnable
    assert int(trainer2.state.step) == 8


def test_schedule_plus_plateau_rejected(mesh8):
    """One LR policy per recipe (VERDICT r2 weak #6): a scheduled LR is
    re-evaluated inside the jitted step and silently overrides plateau
    writes, so the combination is refused at construction."""
    from deep_vision_tpu.configs import ExperimentConfig
    from deep_vision_tpu.train.optimizers import make_schedule

    with pytest.raises(ValueError, match="schedule.*plateau|plateau"):
        ExperimentConfig(
            name="bad", task="classification", model="lenet5",
            schedule={"kind": "step", "step_size_epochs": 10},
            plateau={"factor": 0.1},
        )

    model = get_model("lenet5", num_classes=4)
    tx = build_optimizer(
        "sgd", make_schedule("step", 0.1, step_size=10), momentum=0.9
    )
    with pytest.raises(ValueError, match="schedule"):
        Trainer(
            model, tx, classification_loss_fn,
            sample_input=jnp.zeros((8, 32, 32, 1)),
            mesh=mesh8, plateau=ReduceLROnPlateau(),
        )


@pytest.mark.slow
def test_current_lr_tracks_schedule(mesh8):
    """The logged LR must be the schedule's current value, not NaN
    (VERDICT r2 weak #6): inject_hyperparams re-evaluates scheduled
    hyperparams each step and current_lr reads the live value."""
    from deep_vision_tpu.train.optimizers import make_schedule

    model = get_model("lenet5", num_classes=4)
    sched = make_schedule("step", 0.1, step_size=2, gamma=0.5)
    tx = build_optimizer("sgd", sched, momentum=0.9)
    tr = Trainer(
        model, tx, classification_loss_fn,
        sample_input=jnp.zeros((8, 32, 32, 1)), mesh=mesh8,
    )
    assert np.isclose(tr.current_lr, 0.1)
    images, labels = synthetic_mnist(n=64)
    for batch in batches(images, labels, 16):
        tr.train_step(batch)
    # 4 steps at gamma=0.5, step_size=2: steps 0-1 ran at 0.1, steps 2-3 at
    # 0.05; current_lr reads the LR the LAST applied update used
    assert np.isclose(tr.current_lr, 0.05), tr.current_lr
